#!/usr/bin/env python
"""Hot-standby failover availability capture: seeded KILL_GCS_PRIMARY
(NO restart — the warm standby IS the recovery) against a standby-paired
LocalCluster -> benchmarks/GCS_failover_r23.json.

The r23 acceptance gate, end to end, against a REAL deployment (primary
GCS process + standby GCS process tailing its replication log + node
daemon + worker processes):

 * serve-shaped traffic (named replica actors driven by a driver-side
   request loop) runs ACROSS the primary kill — per-request paths ride
   cached worker addresses and the node-local object store, and control
   RPCs fail over to the promoted standby: gate completion_rate == 1.0;
 * a cluster-backend training gang (allreduce over the GCS KV — the
   plane the kill cuts) is supervised with a control-plane probe over
   BOTH endpoints: the promotion window is classified as a blackout
   (wait -> re-form -> resume), never as rank death: gate trainer
   recoveries == 0 and the loss curve bitwise equal to the
   uninterrupted baseline;
 * an availability sampler polls the pair at 20 Hz for the whole run:
   the serving gap (longest window with NO endpoint answering the data
   plane) must come in strictly under the r13 restart blackout floor
   (GCS_outage_r13.json's scheduled restart_after_s) — a control-plane
   death costs one lease timeout, not a blackout;
 * after promotion the standby runs the same reconcile discipline a
   restarted primary would: gate zero duplicate or lost actors and
   exact telemetry counter convergence, with gcs_restarts_total == 0
   and gcs_failovers_total >= 1 (nobody restarted anything).

Run: JAX_PLATFORMS=cpu python benchmarks/gcs_failover_bench.py [--out PATH]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def req_counter_name(run_tag: str) -> str:
    # per-run metric name: the registry is process-global, so a shared
    # name would carry the baseline run's total into the chaos run and
    # break the exact-convergence comparison
    return f"ray_tpu_bench_failover_requests_{run_tag}_total"


# -- the serve plane (replica actors + driver request loop) -------------------


class BenchReplica:
    def __init__(self, idx):
        self.idx = idx
        self.count = 0

    def serve_one(self, x):
        self.count += 1
        return (self.idx, self.count)

    def stats(self):
        return {"idx": self.idx, "count": self.count}


# -- the training problem (same shape as gcs_outage_bench) --------------------

W_TRUE = np.asarray([1.0, -2.0, 3.0, 0.5])


def init_fn(seed):
    return {"w": np.zeros(4, np.float64)}


def grad_fn(state, batch):
    x, y = batch
    err = x @ state["w"] - y
    return float(np.mean(err ** 2)), {"w": 2 * x.T @ err / len(y)}


def apply_fn(state, grads):
    return {"w": state["w"] - 0.1 * grads["w"]}


def batch_fn(seed, step, world, rank):
    import time as _t

    from ray_tpu.train.elastic import rng_for

    _t.sleep(0.03)  # pace the gang so the horizon spans the failover
    rng = rng_for(seed, step, rank)
    x = rng.normal(size=(8, 4))
    return x, x @ W_TRUE


# -- pair-aware probes ---------------------------------------------------------


def _serving_endpoint(endpoints, timeout=1.0):
    """First endpoint currently serving the data plane as an unfenced
    primary, or None. The standby answers ha_status before promotion —
    role gates it out until it actually owns the tables."""
    from ray_tpu.cluster.rpc import RpcClient

    for ep in endpoints:
        try:
            c = RpcClient(ep[0], ep[1], timeout=timeout).connect(retries=0)
            try:
                st = c.call("ha_status", {}, timeout=timeout)
                if st["role"] == "primary" and not st["fenced"]:
                    return ep
            finally:
                c.close()
        except Exception:  # noqa: BLE001 — dead/dark endpoint
            continue
    return None


def make_probe(endpoints):
    def probe() -> bool:
        from ray_tpu.cluster.rpc import RpcClient

        ep = _serving_endpoint(endpoints, timeout=2.0)
        if ep is None:
            return False
        try:
            c = RpcClient(ep[0], ep[1], timeout=2.0).connect(retries=0)
            try:
                c.call("list_nodes", None, timeout=2.0)
            finally:
                c.close()
            return True
        except Exception:  # noqa: BLE001 — dark is dark
            return False

    return probe


def make_epoch(endpoints):
    """Failover detector for the supervisor: restarts + failovers of the
    currently serving primary. A kill with promotion bumps failovers (at
    zero restarts), so a round spanning the window sees the epoch move
    exactly like r13's restart counter did."""
    def epoch():
        from ray_tpu.cluster.rpc import RpcClient

        ep = _serving_endpoint(endpoints, timeout=2.0)
        if ep is None:
            raise RuntimeError("no serving GCS primary")
        c = RpcClient(ep[0], ep[1], timeout=2.0).connect(retries=0)
        try:
            ft = c.call("gcs_ft", {}, timeout=2.0)
            return (ft["gcs_restarts_total"], ft["gcs_failovers_total"])
        finally:
            c.close()

    return epoch


class AvailabilitySampler:
    """20 Hz data-plane availability poll across the pair. A sample is
    UP when some endpoint serves list_nodes as an unfenced primary; the
    gap is the longest down-window, measured from the last up-sample
    before it to the first up-sample after (i.e. what a client saw)."""

    def __init__(self, endpoints, interval_s: float = 0.05):
        self.endpoints = tuple(endpoints)
        self.interval_s = interval_s
        self.samples: list[tuple[float, bool]] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="availability-sampler", daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=30)

    def _sample_once(self) -> bool:
        from ray_tpu.cluster.rpc import RpcClient

        ep = _serving_endpoint(self.endpoints, timeout=0.75)
        if ep is None:
            return False
        try:
            c = RpcClient(ep[0], ep[1], timeout=0.75).connect(retries=0)
            try:
                c.call("list_nodes", None, timeout=0.75)
            finally:
                c.close()
            return True
        except Exception:  # noqa: BLE001
            return False

    def _run(self):
        while not self._stop.is_set():
            t = time.monotonic()
            ok = self._sample_once()
            self.samples.append((t, ok, time.monotonic() - t))
            self._stop.wait(self.interval_s)

    def report(self) -> dict:
        gap_windows: list[float] = []
        last_up = None
        down_since = None
        for t, ok, _lat in self.samples:
            if ok:
                if down_since is not None:
                    gap_windows.append(t - (last_up if last_up is not None
                                            else down_since))
                    down_since = None
                last_up = t
            elif down_since is None:
                down_since = t
        if down_since is not None and self.samples:
            # still dark at the end: count the open window
            end = self.samples[-1][0]
            gap_windows.append(end - (last_up if last_up is not None
                                      else down_since))
        lat_ms = sorted(lat * 1000.0 for _, _, lat in self.samples)

        def pct(xs, q):
            if not xs:
                return 0.0
            return xs[min(len(xs) - 1, int(q * (len(xs) - 1) + 0.5))]

        gaps_sorted = sorted(gap_windows)
        return {
            "gap_s": round(max(gap_windows, default=0.0), 3),
            "gap_p50_s": round(pct(gaps_sorted, 0.50), 3),
            "gap_p99_s": round(pct(gaps_sorted, 0.99), 3),
            "gaps": len(gap_windows),
            "samples": len(self.samples),
            "down_samples": sum(1 for _, ok, _ in self.samples if not ok),
            "probe_p50_ms": round(pct(lat_ms, 0.50), 3),
            "probe_p99_ms": round(pct(lat_ms, 0.99), 3),
        }


def _run_once(steps: int, world: int, schedule=None, run_tag: str = "run",
              traffic_s: float = 12.0, lease_timeout_s: float = 1.0) -> dict:
    from ray_tpu import chaos
    from ray_tpu.chaos.runner import ChaosRunner
    from ray_tpu.cluster import LocalCluster
    from ray_tpu.core import api
    from ray_tpu.obs.telemetry import TelemetryReporter, cluster_counter
    from ray_tpu.train.elastic import ElasticConfig, TrainerSupervisor

    out: dict = {}
    with tempfile.TemporaryDirectory() as ckpt_root:
        with LocalCluster(node_death_timeout_s=2.0, standby=True,
                          gcs_lease_timeout_s=lease_timeout_s) as c:
            c.start()
            c.add_node({"num_cpus": 8}, node_id="head")
            c.wait_for_nodes(1)
            endpoints = c.gcs_endpoints
            client = c.client()
            api.init(address=c.address, ignore_reinit_error=True)
            sampler = None
            try:
                replicas = [
                    client.create_actor(
                        BenchReplica, (i,), name=f"replica-{i}",
                        max_restarts=1,
                    )
                    for i in range(2)
                ]
                counter_name = req_counter_name(run_tag)
                req_counter = cluster_counter(
                    counter_name,
                    description="failover bench: completed serve requests",
                )
                reporter = TelemetryReporter(
                    gcs_addr=endpoints, reporter_id="bench-driver",
                    kind="bench", interval_s=0.25, timeout_s=2.0,
                    series_filter=lambda name, tags: name.startswith(
                        "ray_tpu_bench_"
                    ),
                ).start()

                sent = [0]
                completed = [0]
                failures: list = []
                stop_traffic = threading.Event()

                def traffic():
                    i = 0
                    # hard cap well past any plausible run; the stop
                    # event (set when the trainer finishes) is the real
                    # terminator, so traffic is GUARANTEED to span the
                    # whole promotion window
                    deadline = time.monotonic() + traffic_s + 240
                    while time.monotonic() < deadline \
                            and not stop_traffic.is_set():
                        h = replicas[i % len(replicas)]
                        i += 1
                        sent[0] += 1
                        try:
                            client.get(h.serve_one.remote(i), timeout=60)
                            completed[0] += 1
                            req_counter.inc()
                        except Exception as e:  # noqa: BLE001
                            failures.append(repr(e))
                        time.sleep(0.01)

                sup = TrainerSupervisor(
                    init_fn=init_fn, grad_fn=grad_fn, apply_fn=apply_fn,
                    batch_fn=batch_fn, total_steps=steps,
                    checkpoint_root=ckpt_root,
                    config=ElasticConfig(
                        world_size=world, backend="cluster",
                        group_name="failover_gang", seed=7,
                        step_timeout_s=2.0, checkpoint_every=4,
                        sharded_checkpoints=False,
                        control_plane_probe=make_probe(endpoints),
                        control_plane_epoch=make_epoch(endpoints),
                        blackout_wait_s=30.0,
                    ),
                )
                train_res: list = [None]

                def train():
                    train_res[0] = sup.fit()

                t0 = time.monotonic()
                tt = threading.Thread(target=traffic, daemon=True)
                tr = threading.Thread(target=train, daemon=True)
                tt.start()
                tr.start()

                # arm the kill only once the gang is formed, the standby
                # has synced, and traffic is warm — a kill that lands
                # before the standby bootstraps tests the r13 path, not
                # the failover
                runner = None
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    try:
                        infos = client.gcs.call(
                            "list_actors", None, timeout=5
                        )
                        alive = [
                            a for a in infos if a["state"] == "ALIVE"
                        ]
                        st = client.gcs.call("ha_status", {}, timeout=5)
                        if len(alive) >= 2 + world \
                                and completed[0] >= 20 \
                                and st.get("replication_lag_s") is not None:
                            break
                    except Exception:  # noqa: BLE001
                        pass
                    time.sleep(0.1)
                sampler = AvailabilitySampler(endpoints).start()
                if schedule is not None:
                    chaos.install(schedule)
                    runner = ChaosRunner(schedule, cluster=c).start()

                tr.join(timeout=300)
                stop_traffic.set()
                tt.join(timeout=120)
                wall_s = time.monotonic() - t0
                if runner is not None:
                    runner.join(timeout=60)
                sampler.stop()

                # -- post-promotion reconcile + convergence --------------
                ft = {}
                deadline = time.monotonic() + 20
                while time.monotonic() < deadline:
                    try:
                        ft = client.gcs.call("gcs_ft", {}, timeout=5)
                        if schedule is None or (
                            ft.get("reconcile_nodes_reregistered", 0) >= 1
                            and ft.get("actors_pending_confirm", 0) == 0
                        ):
                            break
                    except Exception:  # noqa: BLE001
                        pass
                    time.sleep(0.25)

                local_total = float(completed[0])
                converged = False
                remote_total = None
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    try:
                        agg = client.cluster_metrics()
                        acc = agg.get("counters", {}).get(counter_name)
                        remote_total = (
                            float(acc["total"]) if acc is not None else None
                        )
                        if remote_total == local_total:
                            converged = True
                            break
                    except Exception:  # noqa: BLE001
                        pass
                    time.sleep(0.25)

                infos = client.gcs.call("list_actors", None, timeout=10)
                alive = [a for a in infos if a["state"] == "ALIVE"]
                ids = [a["actor_id"] for a in infos]
                replica_counts = [
                    client.get(h.stats.remote(), timeout=30)["count"]
                    for h in replicas
                ]
                ha = client.gcs.call("ha_status", {}, timeout=10)
                res = train_res[0]
                reporter.stop(final_push=True)

                out = {
                    "wall_s": round(wall_s, 3),
                    "serve": {
                        "sent": sent[0],
                        "completed": completed[0],
                        "completion_rate": (
                            completed[0] / sent[0] if sent[0] else 0.0
                        ),
                        "failures": failures[:10],
                        "replica_counts": replica_counts,
                        "replica_total": sum(replica_counts),
                    },
                    "actors": {
                        "created": 2 + (res.final_world_size if res else 0),
                        "alive": len(alive),
                        "duplicate_ids": len(ids) - len(set(ids)),
                        "replicas_alive": sum(
                            1 for a in alive
                            if (a.get("name") or "").startswith("replica-")
                        ),
                    },
                    "trainer": None if res is None else {
                        "completed": res.completed,
                        "steps": len(res.losses),
                        "losses": res.losses,
                        "recoveries": len(res.recoveries),
                        "blackouts": len(res.blackouts),
                        "blackout_log": [
                            dataclasses.asdict(r) for r in res.blackouts
                        ],
                        "final_gen": res.final_gen,
                    },
                    "telemetry": {
                        "local_total": local_total,
                        "remote_total": remote_total,
                        "convergent": converged,
                    },
                    "availability": sampler.report(),
                    "ha": ha,
                    "gcs_ft": ft,
                }
            finally:
                if sampler is not None:
                    sampler.stop()
                api.shutdown()
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=120)
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--seed", type=int, default=23)
    # measured from runner arming (which waits for the gang to form, the
    # standby to sync, and traffic to warm), so a small offset reliably
    # lands mid-training
    ap.add_argument("--kill-at-s", type=float, default=1.5)
    ap.add_argument("--lease-timeout-s", type=float, default=1.0)
    ap.add_argument("--traffic-s", type=float, default=12.0)
    ap.add_argument(
        "--out",
        default=os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "GCS_failover_r23.json"),
    )
    args = ap.parse_args()

    from ray_tpu.chaos import KILL_GCS_PRIMARY, FaultSchedule, FaultSpec

    base = _run_once(args.steps, args.world, schedule=None,
                     run_tag="baseline", traffic_s=args.traffic_s,
                     lease_timeout_s=args.lease_timeout_s)
    if not base["trainer"]["completed"] or \
            base["serve"]["completion_rate"] != 1.0:
        print("baseline failed", file=sys.stderr)
        print(json.dumps(base, indent=2, default=str), file=sys.stderr)
        return 1

    schedule = FaultSchedule(args.seed, [
        FaultSpec(kind=KILL_GCS_PRIMARY, at_s=args.kill_at_s),
    ])
    chaos_run = _run_once(args.steps, args.world, schedule=schedule,
                          run_tag="chaos", traffic_s=args.traffic_s,
                          lease_timeout_s=args.lease_timeout_s)
    fired = [{"kind": f.kind, "site": f.site, "seq": f.seq}
             for f in schedule.log]

    base_losses = base["trainer"]["losses"]
    chaos_losses = chaos_run["trainer"]["losses"]
    identical = (
        len(base_losses) == len(chaos_losses)
        and all(a == b for a, b in zip(base_losses, chaos_losses))
    )
    for run in (base, chaos_run):
        run["trainer"].pop("losses", None)

    # the r13 restart blackout is the floor the failover must beat: its
    # scheduled dark window is a hard lower bound on what the restart
    # path could ever deliver
    r13_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "GCS_outage_r13.json")
    with open(r13_path) as f:
        r13_floor = float(json.load(f)["config"]["restart_after_s"])
    gap = chaos_run["availability"]["gap_s"]

    out = {
        "bench": "gcs_failover",
        "rev": "r23",
        "platform": "cpu",
        "config": {
            "steps": args.steps,
            "world_size": args.world,
            "seed": args.seed,
            "kill_at_s": args.kill_at_s,
            "lease_timeout_s": args.lease_timeout_s,
            "traffic_s": args.traffic_s,
            "r13_blackout_floor_s": r13_floor,
        },
        "baseline": base,
        "chaos": chaos_run,
        "loss_identical": identical,
        "faults_fired": fired,
    }

    from ray_tpu.obs.perfwatch import ledger

    ledger.write_capture(
        args.out, out, bench="gcs_failover", rev="r23",
        metrics={
            "availability_gap_s": ledger.metric(
                gap, unit="s", better=ledger.BETTER_LOWER, abs_tol=0.5),
            "serve_completion_rate": ledger.metric(
                chaos_run["serve"]["completion_rate"], unit="ratio",
                better=ledger.BETTER_HIGHER, rel_tol=0.0),
            "gcs_failovers_total": ledger.metric(
                chaos_run["gcs_ft"].get("gcs_failovers_total", 0),
                unit="count", better=ledger.BETTER_LOWER, abs_tol=1.0),
        },
    )
    print(json.dumps({
        "serve_completion": chaos_run["serve"]["completion_rate"],
        "trainer_recoveries": chaos_run["trainer"]["recoveries"],
        "trainer_blackouts": chaos_run["trainer"]["blackouts"],
        "loss_identical": identical,
        "telemetry_convergent": chaos_run["telemetry"]["convergent"],
        "availability": chaos_run["availability"],
        "r13_blackout_floor_s": r13_floor,
        "ha": chaos_run["ha"],
        "gcs_ft": chaos_run["gcs_ft"],
    }, indent=2, default=str))
    print(f"\nwrote {args.out}")

    failed = (
        chaos_run["serve"]["completion_rate"] != 1.0
        or not chaos_run["trainer"]["completed"]
        or chaos_run["trainer"]["recoveries"] != 0
        or not identical
        or chaos_run["actors"]["duplicate_ids"] != 0
        or chaos_run["actors"]["replicas_alive"] != 2
        or chaos_run["serve"]["replica_total"]
        != chaos_run["serve"]["completed"]
        or not chaos_run["telemetry"]["convergent"]
        or chaos_run["gcs_ft"].get("gcs_failovers_total", 0) < 1
        or chaos_run["gcs_ft"].get("gcs_restarts_total", 0) != 0
        or chaos_run["ha"].get("role") != "primary"
        or chaos_run["ha"].get("term", 0) < 1
        or gap >= r13_floor
        or "kill_gcs_primary" not in {e["kind"] for e in fired}
    )
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
