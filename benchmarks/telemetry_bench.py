#!/usr/bin/env python
"""Telemetry-plane capture: 2-node + 2-pool in-process cluster under
injected telemetry-push drops -> benchmarks/TELEM_cluster_r11.json.

What it exercises end to end (the r11 acceptance gate):

 * two REAL node daemons piggybacking metrics snapshots on heartbeats to
   a real GCS server; two tiny LLM engines (prefill-pool / decode-pool
   model tags) serving real CPU traffic with per-engine reporters
   pushing over the telemetry_push RPC;
 * seeded chaos DROP on telemetry_push while a ground-truth counter
   ticks: the aggregate must stay monotonic through the fault window and
   converge to EXACTLY the ground truth after it (drops cost freshness,
   never counts);
 * merged-histogram correctness: the GCS-served TTFT percentiles per
   pool must match percentiles over the union of raw per-request TTFT
   observations (pulled from the flight recorder) within one bucket
   width;
 * `ray_tpu status` rendering with per-pool SLO grades sourced from GCS
   aggregation alone.

Run: JAX_PLATFORMS=cpu python benchmarks/telemetry_bench.py [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STALENESS_BOUND_S = 5.0


def _raw_ttfts_since(known_trace_ids):
    """Per-request TTFT observations from the flight recorder for traces
    not yet attributed to a pool (sequential traffic per pool makes
    attribution by delta exact)."""
    from ray_tpu import obs

    rec = obs.get_recorder()
    out, seen = [], set()
    for meta in rec.traces(limit=10_000):
        tid = meta["trace_id"]
        seen.add(tid)
        if tid in known_trace_ids:
            continue
        for s in rec.get(tid):
            if s.name == "llm.request" and "ttft_s" in s.attrs:
                out.append(float(s.attrs["ttft_s"]))
    return out, seen


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "TELEM_cluster_r11.json"
    ))
    p.add_argument("--seed", type=int, default=1234)
    args = p.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from ray_tpu.chaos import harness
    from ray_tpu.chaos.schedule import DROP_RPC, FaultSchedule, FaultSpec
    from ray_tpu.cluster.gcs_service import GcsServer
    from ray_tpu.cluster.node_daemon import NodeDaemon
    from ray_tpu.cluster.rpc import RpcClient
    from ray_tpu.llm.engine import EngineConfig, LLMEngine
    from ray_tpu.llm.sampling import SamplingParams
    from ray_tpu.models import llama
    from ray_tpu.obs import telemetry
    from ray_tpu.serve.controller import replica_gauges
    from ray_tpu.util import metrics as metrics_mod

    t_start = time.time()
    server = GcsServer(port=0)
    gcs_addr = server.start()
    store = server.service.telemetry
    daemons = [
        NodeDaemon(
            gcs_addr, {"num_cpus": 2}, node_id=f"bench-n{i}",
            heartbeat_interval_s=0.1, telemetry_interval_s=0.2,
            memory_monitor_interval_s=0,
        )
        for i in range(2)
    ]
    for d in daemons:
        d.start()

    # -- two pools: tiny engines, real CPU traffic -------------------------
    cfg = dict(model=llama.LLAMA_TINY, num_blocks=64, max_num_seqs=4,
               max_prefill_len=64)
    pools = {
        "bench-prefill-pool": LLMEngine(EngineConfig(**cfg), seed=0),
        "bench-decode-pool": LLMEngine(EngineConfig(**cfg), seed=1),
    }
    rng = np.random.default_rng(args.seed)
    sp = SamplingParams(max_tokens=8, temperature=0.0, ignore_eos=True)

    def prompts(n):
        return [
            list(map(int, rng.integers(3, 500, size=int(k))))
            for k in rng.integers(6, 14, size=n)
        ]

    for tag, eng in pools.items():
        eng.model_tag = tag
        # warmup at the measured batch size: the capture's SLO numbers
        # must price serving, not one-off XLA compiles
        eng.generate(prompts(6), sp)

    # measured phase starts from a clean registry (warmup compile times
    # must not pollute the SLO histograms) and BEFORE any telemetry push
    metrics_mod.clear_registry()
    from ray_tpu import obs

    obs.get_recorder().clear()

    raw_ttfts: dict = {}
    seen_traces: set = set()
    for tag, eng in pools.items():
        eng.generate(prompts(6), sp)
        eng.update_telemetry_gauges()
        raw_ttfts[tag], seen_traces = _raw_ttfts_since(seen_traces)

    g = replica_gauges()
    for role, dep in (("prefill", "PrefillPool"), ("decode", "DecodePool")):
        tags = {"app": "llm", "deployment": dep, "role": role}
        g["running"].set(1, tags=tags)
        g["target"].set(1, tags=tags)

    ticks = telemetry.cluster_counter(
        "llm_bench_ticks_total",
        "telemetry bench ground-truth ticks (drop-injection audit)",
    )

    def engine_filter(tag):
        # ONLY series tagged with this engine's model tag: an untagged
        # series shipped by several reporters would be summed once per
        # reporter (exactly the double count the ticks audit exists to
        # catch)
        return lambda name, t: (
            name.startswith("ray_tpu_llm_") and t.get("model") == tag
        )

    reporters = [
        telemetry.TelemetryReporter(
            gcs_addr, reporter_id=tag, kind="engine",
            role="prefill" if "prefill" in tag else "decode",
            series_filter=engine_filter(tag),
            collect=[eng.update_telemetry_gauges],
        )
        for tag, eng in pools.items()
    ]
    driver = telemetry.TelemetryReporter(
        gcs_addr, reporter_id="bench-driver", kind="driver",
        series_filter=lambda name, t: name.startswith(
            ("ray_tpu_serve_", "ray_tpu_llm_bench_")
        ),
    )
    reporters.append(driver)

    # -- fault window: seeded DROP on telemetry_push -----------------------
    rpc = RpcClient(*gcs_addr).connect()
    schedule = FaultSchedule(args.seed, [
        FaultSpec(kind=DROP_RPC, site="rpc.call",
                  match={"method": "telemetry_push"}, p=0.5),
    ])
    harness.install(schedule)
    ground_truth = 0
    totals = []
    dropped = ok = 0
    try:
        for _ in range(12):
            ticks.inc(1)
            ground_truth += 1
            for r in reporters:
                if r.push_once():
                    ok += 1
                else:
                    dropped += 1
            agg = rpc.call("telemetry_cluster", {})
            acc = agg["counters"].get("ray_tpu_llm_bench_ticks_total")
            totals.append(acc["total"] if acc else 0.0)
    finally:
        harness.uninstall()
    monotonic = all(b >= a for a, b in zip(totals, totals[1:]))
    never_over = all(t <= ground_truth for t in totals)
    # fault window over: one clean push converges exactly
    for r in reporters:
        assert r.push_once(), "clean push failed with chaos uninstalled"
    agg = rpc.call("telemetry_cluster", {})
    aggregated = agg["counters"]["ray_tpu_llm_bench_ticks_total"]["total"]

    # -- wait for both node daemons to report via heartbeat piggyback ------
    deadline = time.monotonic() + 15
    node_ids = {d.node_id for d in daemons}
    while time.monotonic() < deadline:
        reps = rpc.call("telemetry_cluster", {})["reporters"]
        if node_ids <= set(reps):
            break
        time.sleep(0.05)
    reps = rpc.call("telemetry_cluster", {})["reporters"]
    nodes_reporting = sum(1 for n in node_ids if n in reps)
    staleness = rpc.call("telemetry_cluster", {})["staleness"]
    staleness_max = max(
        (v for k, v in staleness.items()
         if k in node_ids or any(k == r.reporter_id for r in reporters)),
        default=float("inf"),
    )

    # -- merged-histogram correctness vs union of raw observations ---------
    agg = rpc.call("telemetry_cluster", {})
    hist_pools = {}
    within = True
    ttft_name = telemetry.SLO_HISTOGRAMS["ttft"]
    for tag, raw in raw_ttfts.items():
        merged = agg["histograms"][ttft_name]["series"][f"model={tag}"]
        union = sorted(raw)
        checks = {}
        for q in (50.0, 95.0):
            rank = max(1, math.ceil(q / 100.0 * len(union)))
            true_v = union[rank - 1]
            band = telemetry.bucket_percentile_band(
                merged["boundaries"], merged["buckets"], q
            )
            lo, hi = band
            in_band = (lo < true_v <= hi) or (hi == float("inf") and true_v > lo)
            within = within and in_band
            checks[f"p{q:g}"] = {
                "merged_estimate": merged[f"p{q:g}"],
                "union_value": round(true_v, 6),
                "bucket": [lo, None if hi == float("inf") else hi],
                "in_band": in_band,
            }
        assert merged["count"] == len(union), (merged["count"], len(union))
        hist_pools[tag] = {"count": merged["count"], **checks}

    # -- SLO grades + pools + status from the one-query status RPC ---------
    status = rpc.call("telemetry_status", {})
    status_text = telemetry.format_status(status)

    out = {
        "capture": "telemetry plane: 2-node + 2-pool in-process cluster, "
        "CPU engines, seeded telemetry_push drops (p=0.5)",
        "unix_time": round(t_start, 1),
        "wall_s": round(time.time() - t_start, 2),
        "chaos_seed": args.seed,
        "num_nodes": len(daemons),
        "nodes_reporting": nodes_reporting,
        "staleness_max_s": round(staleness_max, 3),
        "staleness_bound_s": STALENESS_BOUND_S,
        "pushes_ok": ok,
        "pushes_dropped": dropped,
        "counter_ground_truth": float(ground_truth),
        "counter_aggregated": float(aggregated),
        "aggregate_monotonic": bool(monotonic and never_over),
        "observed_totals": totals,
        "hist_check": {"within_one_bucket": bool(within), "pools": hist_pools},
        "slo": status["slo"],
        "pools": status["pools"],
        "utilization": status["utilization"],
        "status_text": status_text,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2, default=str)
        f.write("\n")
    print(status_text)
    print(f"\nwrote {args.out}")
    print(
        f"nodes {nodes_reporting}/{len(daemons)} reporting, "
        f"staleness max {staleness_max:.3f}s, "
        f"drops {dropped}/{ok + dropped} pushes, "
        f"counter {aggregated}/{ground_truth}, "
        f"hist within-one-bucket: {within}"
    )
    rpc.close()
    for r in reporters:
        r.stop(final_push=False)
    for d in daemons:
        d.stop()
    server.stop()
    failed = (
        nodes_reporting != len(daemons)
        or staleness_max > STALENESS_BOUND_S
        or aggregated != ground_truth
        or not (monotonic and never_over)
        or not within
        or dropped < 1
    )
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
