"""Shared route-table cache for Serve front ends.

Both ingress tiers (HTTP proxy, RPC ingress) consume the controller's
route table; one TTL'd cache keeps their polling behavior — and any
future change to the table's shape — in a single place.
"""

from __future__ import annotations

import time
from typing import Any


class RouteTableCache:
    """TTL'd view of controller.list_routes: {prefix: (app, ingress)}."""

    def __init__(self, controller_handle, ttl_s: float = 0.5):
        self._controller = controller_handle
        self._ttl = ttl_s
        self._routes: dict = {}
        self._stamp = 0.0

    def get(self) -> dict:
        import ray_tpu

        if time.time() - self._stamp >= self._ttl or not self._routes:
            self._routes = ray_tpu.get(self._controller.list_routes.remote())
            self._stamp = time.time()
        return self._routes

    def match(self, path: str) -> "Any | None":
        """Longest-prefix route match -> (norm, prefix, app, ingress)."""
        best = None
        for prefix, (app, ingress) in self.get().items():
            norm = prefix.rstrip("/") or "/"
            if path == norm or path.startswith(norm + "/") or norm == "/":
                if best is None or len(norm) > len(best[0]):
                    best = (norm, prefix, app, ingress)
        return best


class AppResolver:
    """Shared app-name resolution + DeploymentHandle cache for the
    non-HTTP ingresses (framed-RPC and gRPC front doors): both route by
    app name with a single-app default and memoize handles per
    (app, ingress). One implementation, one drift surface."""

    def __init__(self, controller_handle, error_cls: type = KeyError):
        import threading

        self.route_cache = RouteTableCache(controller_handle)
        self._handles: dict = {}
        self._lock = threading.Lock()
        self._error_cls = error_cls

    def resolve(self, app: "str | None") -> tuple:
        apps = {a: ingress for _, (a, ingress) in self.route_cache.get().items()}
        if app is None:
            if not apps:
                raise self._error_cls(
                    "no applications with a route_prefix are deployed"
                )
            if len(apps) > 1:
                raise self._error_cls(
                    f"app selection required: multiple apps deployed "
                    f"({sorted(apps)})"
                )
            app = next(iter(apps))
        ingress = apps.get(app)
        if ingress is None:
            raise self._error_cls(
                f"no deployed app {app!r}; have {sorted(apps)}"
            )
        return app, ingress

    def handle_for(self, app: str, ingress: str):
        with self._lock:
            h = self._handles.get((app, ingress))
            if h is None:
                from ray_tpu.serve.handle import DeploymentHandle

                h = DeploymentHandle(ingress, app)
                self._handles[(app, ingress)] = h
            return h
