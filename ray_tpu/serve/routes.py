"""Shared route-table cache for Serve front ends.

Both ingress tiers (HTTP proxy, RPC ingress) consume the controller's
route table; one TTL'd cache keeps their polling behavior — and any
future change to the table's shape — in a single place.
"""

from __future__ import annotations

import time
from typing import Any


class RouteTableCache:
    """TTL'd view of controller.list_routes: {prefix: (app, ingress)}."""

    def __init__(self, controller_handle, ttl_s: float = 0.5):
        self._controller = controller_handle
        self._ttl = ttl_s
        self._routes: dict = {}
        self._stamp = 0.0

    def get(self) -> dict:
        import ray_tpu

        if time.time() - self._stamp >= self._ttl or not self._routes:
            self._routes = ray_tpu.get(self._controller.list_routes.remote())
            self._stamp = time.time()
        return self._routes

    def match(self, path: str) -> "Any | None":
        """Longest-prefix route match -> (norm, prefix, app, ingress)."""
        best = None
        for prefix, (app, ingress) in self.get().items():
            norm = prefix.rstrip("/") or "/"
            if path == norm or path.startswith(norm + "/") or norm == "/":
                if best is None or len(norm) > len(best[0]):
                    best = (norm, prefix, app, ingress)
        return best
