"""gRPC ingress: the standards-based front door next to HTTP and the
framed-RPC ingress.

Reference analog: Serve's gRPCProxy (python/ray/serve/_private/
proxy.py:532) — user-defined protobuf service methods routed to
deployments. Redesigned without a protoc step on the SERVER side: a
`grpc.GenericRpcHandler` accepts ANY ``/package.Service/Method`` call,
routes it through the same controller route table the HTTP proxy uses,
and passes the request's raw serialized bytes to the deployment. The
contract mirrors the reference's:

  * the app is selected with the ``application`` request metadata key
    (single deployed app = default, like the reference);
  * the deployment method invoked is the gRPC method name (``Predict``
    for ``/user.Inference/Predict``); ``Call`` or ``__call__`` target
    the ingress deployment's ``__call__``;
  * deployments receive the request message's serialized bytes and
    return bytes (parse/serialize with their own generated protobuf
    classes — clients use their normal generated stubs unchanged).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.serve.grpc_ingress")


class GrpcIngress:
    def __init__(self, host: str, port: int, controller_handle,
                 max_workers: int = 16):
        import grpc
        from concurrent import futures

        from ray_tpu.serve.routes import AppResolver

        self._resolver = AppResolver(controller_handle, error_cls=KeyError)
        outer = self

        class _Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                method = handler_call_details.method
                md = dict(handler_call_details.invocation_metadata or ())
                return grpc.unary_unary_rpc_method_handler(
                    lambda request, ctx: outer._dispatch(
                        method, md, request, ctx
                    ),
                    # (de)serializers None: raw message bytes in and out
                )

        self._grpc = grpc
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max_workers, thread_name_prefix="serve-grpc"
            )
        )
        self._server.add_generic_rpc_handlers((_Handler(),))
        bound = self._server.add_insecure_port(f"{host}:{port}")
        if bound == 0:
            raise RuntimeError(f"gRPC ingress failed to bind {host}:{port}")
        self.addr = (host, bound)
        self._server.start()

    def _dispatch(self, method: str, metadata: dict, request: bytes, ctx):
        grpc = self._grpc
        try:
            # ROUTING errors only in this block: a deployment's own
            # KeyError must not masquerade as NOT_FOUND (clients key
            # retry/re-resolve behavior on that status)
            app, ingress = self._resolver.resolve(metadata.get("application"))
            handle = self._resolver.handle_for(app, ingress)
        except KeyError as e:
            ctx.abort(grpc.StatusCode.NOT_FOUND, str(e))
        try:
            mname = method.rsplit("/", 1)[-1]
            if mname not in ("Call", "__call__"):
                handle = getattr(handle, mname)
            timeout = float(metadata.get("request_timeout_s", 120.0))
            # honor the CLIENT's gRPC deadline: once the caller gives up
            # there is no point pinning a worker thread for the rest of
            # the server-side budget (16 abandoned calls would wedge the
            # whole pool)
            remaining = ctx.time_remaining()
            if remaining is not None:
                timeout = min(timeout, max(0.1, remaining))
            out = handle.remote(request).result(timeout_s=timeout)
        except Exception as e:  # noqa: BLE001 — deployment-level failure
            # both timeout types: core GetTimeoutError subclasses
            # TimeoutError, the cluster one is a plain Exception
            from ray_tpu.cluster.client import GetTimeoutError as _CGTE

            if isinstance(e, (TimeoutError, _CGTE)):
                ctx.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
            logger.exception("gRPC ingress call failed")
            ctx.abort(grpc.StatusCode.INTERNAL, repr(e))
        if out is None:
            return b""
        if isinstance(out, (bytes, bytearray, memoryview)):
            return bytes(out)
        serialize = getattr(out, "SerializeToString", None)
        if serialize is not None:  # a protobuf message object
            return serialize()
        ctx.abort(
            grpc.StatusCode.INTERNAL,
            f"deployment returned {type(out).__name__}; gRPC responses must "
            "be bytes or protobuf messages",
        )

    def shutdown(self) -> None:
        # wait out the grace window: serve.shutdown() kills the
        # controller right after this returns, and draining RPCs must
        # finish against a live control plane
        self._server.stop(grace=1.0).wait()
