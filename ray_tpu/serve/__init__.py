"""ray_tpu.serve: online model serving.

TPU-first re-design of the reference's Ray Serve (python/ray/serve/):
controller reconciliation loop, power-of-two-choices routing,
deployment handles with dataflow composition, queue-depth autoscaling,
and an aiohttp ingress proxy. See SURVEY.md §2.5 / §3.5.
"""

from ray_tpu.serve.api import (
    Application,
    Deployment,
    delete,
    deployment,
    get_app_handle,
    get_deployment_handle,
    run,
    shutdown,
    start,
    start_grpc_ingress,
    start_rpc_ingress,
    status,
)
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig, HTTPOptions
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.proxy import Request

__all__ = [
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentConfig",
    "DeploymentHandle",
    "DeploymentResponse",
    "HTTPOptions",
    "Request",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "run",
    "shutdown",
    "start",
    "start_grpc_ingress",
    "start_rpc_ingress",
    "status",
]
