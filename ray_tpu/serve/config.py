"""Serve configuration types.

Reference analogs: python/ray/serve/config.py (AutoscalingConfig,
HTTPOptions) and python/ray/serve/_private/config.py (DeploymentConfig,
ReplicaConfig). Kept as plain dataclasses — the reference uses pydantic,
but these cross no wire here (single-host control plane), so validation
lives in __post_init__.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional


@dataclass
class AutoscalingConfig:
    """Queue-depth proportional autoscaling (reference:
    python/ray/serve/config.py AutoscalingConfig + autoscaling_policy.py
    _calculate_desired_num_replicas)."""

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    # Seconds between autoscaling decisions and smoothing of the signal.
    metrics_interval_s: float = 0.5
    look_back_period_s: float = 2.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 2.0
    upscaling_factor: float = 1.0
    downscaling_factor: float = 1.0
    initial_replicas: Optional[int] = None

    def __post_init__(self):
        if self.min_replicas < 0:
            raise ValueError("min_replicas must be >= 0")
        if self.max_replicas < max(1, self.min_replicas):
            raise ValueError("max_replicas must be >= max(1, min_replicas)")
        if self.target_ongoing_requests <= 0:
            raise ValueError("target_ongoing_requests must be > 0")

    def desired_replicas(self, total_ongoing: float, current: int) -> int:
        """Proportional control law: replicas ~ total load / per-replica target."""
        if current == 0:
            return max(self.min_replicas, 1 if total_ongoing > 0 else 0)
        error_ratio = total_ongoing / (current * self.target_ongoing_requests)
        if error_ratio > 1:
            desired = current * (1 + (error_ratio - 1) * self.upscaling_factor)
        else:
            desired = current * (1 - (1 - error_ratio) * self.downscaling_factor)
        import math

        desired = math.ceil(desired - 1e-9)
        return max(self.min_replicas, min(self.max_replicas, desired))


@dataclass
class DeploymentConfig:
    """Per-deployment runtime knobs (reference:
    python/ray/serve/_private/config.py DeploymentConfig)."""

    num_replicas: int = 1
    max_ongoing_requests: int = 100
    max_queued_requests: int = -1  # -1 = unbounded
    user_config: Any = None
    # deployment role tag ("prefill" / "decode" for disaggregated LLM
    # serving, "" for ordinary deployments): carried through the
    # controller's replica listings and serve.status so operators and
    # pool-aware clients can tell the pools apart
    role: str = ""
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 2.0
    health_check_timeout_s: float = 30.0
    graceful_shutdown_timeout_s: float = 10.0

    def target_initial_replicas(self) -> int:
        ac = self.autoscaling_config
        if ac is None:
            return self.num_replicas
        if ac.initial_replicas is not None:
            return ac.initial_replicas
        return max(ac.min_replicas, min(ac.max_replicas, 1))


@dataclass
class ReplicaConfig:
    """What to run in each replica: the user callable + actor resources
    (reference: _private/config.py ReplicaConfig)."""

    callable_factory: Callable[[], Any]  # builds the user class/fn instance
    init_args: tuple = ()
    init_kwargs: dict = field(default_factory=dict)
    num_cpus: float = 1.0
    num_tpus: float = 0.0
    resources: dict = field(default_factory=dict)
    is_function: bool = False


@dataclass
class HTTPOptions:
    """Proxy options (reference: python/ray/serve/config.py HTTPOptions)."""

    host: str = "127.0.0.1"
    port: int = 8000
    root_path: str = ""


@dataclass
class ProxyStatus:
    node_id: str
    status: str  # STARTING | HEALTHY | UNHEALTHY | DRAINING


class DeploymentStatus:
    UPDATING = "UPDATING"
    HEALTHY = "HEALTHY"
    UNHEALTHY = "UNHEALTHY"
    UPSCALING = "UPSCALING"
    DOWNSCALING = "DOWNSCALING"


class ReplicaState:
    STARTING = "STARTING"
    RUNNING = "RUNNING"
    STOPPING = "STOPPING"
    DEAD = "DEAD"


class ApplicationStatus:
    DEPLOYING = "DEPLOYING"
    RUNNING = "RUNNING"
    DEPLOY_FAILED = "DEPLOY_FAILED"
    DELETING = "DELETING"
    UNHEALTHY = "UNHEALTHY"
