"""DeploymentHandle / DeploymentResponse — the composition API.

Reference analog: python/ray/serve/handle.py (DeploymentHandle.remote
:625,701, DeploymentResponse, DeploymentResponseGenerator). A response
can be passed directly as an argument to another handle call — the
underlying ObjectRef is substituted so the downstream replica receives
the resolved value (same dataflow composition the reference supports).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ray_tpu.serve.router import Router


class _RouteSlot:
    """One dispatch's inflight accounting; shared with a GC finalizer so
    fire-and-forget calls (response dropped without .result()) still
    decrement the router's count exactly once. When the caller carried a
    TraceContext, completion also records the `serve.request` span
    (dispatch -> result consumed) into the flight recorder."""

    def __init__(self, router: Router, rid: str, span_info: Optional[tuple] = None):
        self._router = router
        self._rid = rid
        self._span_info = span_info  # (ctx, parent_span_id, t0, attrs)
        self._done = False
        self._lock = threading.Lock()

    def complete(self, record_span: bool = True):
        with self._lock:
            if self._done:
                return
            self._done = True
        self._router.complete(self._rid)
        # record_span=False on the GC-finalizer path: a fire-and-forget
        # response may be collected seconds after the call finished, and
        # stamping end=now there would invent phantom request latency
        if record_span and self._span_info is not None:
            try:
                import time

                from ray_tpu.obs import Span, get_recorder

                ctx, parent_span_id, t0, attrs = self._span_info
                get_recorder().add(Span(
                    trace_id=ctx.trace_id,
                    span_id=ctx.span_id,
                    parent_id=parent_span_id,
                    name="serve.request",
                    start=t0,
                    end=time.time(),
                    attrs=attrs,
                ))
            except Exception:  # noqa: BLE001 — tracing must not fail calls
                pass


def _is_replica_failure(exc: BaseException) -> bool:
    """Did this call die with the REPLICA (system failure) rather than in
    user code? Matched by type name so the core-mode errors
    (ray_tpu.core.errors), the cluster-mode twins (cluster/client.py),
    and chaos-injected crashes all count, wherever they sit in a
    TaskError/ClusterTaskError cause chain."""
    names = {
        "ActorDiedError", "ActorUnavailableError", "WorkerCrashedError",
        "ReplicaCrashed",
    }
    seen: set = set()
    stack: list = [exc]
    while stack:
        e = stack.pop()
        if e is None or id(e) in seen:
            continue
        seen.add(id(e))
        if type(e).__name__ in names:
            return True
        stack.append(getattr(e, "cause", None))
        stack.append(e.__cause__)
    return False


def _record_failover(app: str, deployment: str, failed_rid: str,
                     exc: BaseException, attempt: int) -> None:
    """serve.failover event into the flight recorder: the post-mortem
    shows which replica died and that the request re-homed."""
    try:
        import time

        from ray_tpu.obs import get_recorder

        now = time.time()
        get_recorder().record(
            "serve.failover", now, now,
            attrs={
                "app": app, "deployment": deployment,
                "failed_replica": failed_rid, "attempt": attempt,
                "error": f"{type(exc).__name__}: {exc}"[:200],
            },
            status="error",
        )
    except Exception:  # noqa: BLE001
        pass


class DeploymentResponse:
    """Future for one unary handle call.

    When the call carries retry info (unary, retries enabled on the
    handle), a SYSTEM failure — the replica died or crashed mid-request,
    not a user exception — re-dispatches onto a healthy replica, with the
    dead one evicted from the router set. User-code errors and timeouts
    propagate untouched; in-flight work on a dead replica is assumed
    idempotent by the caller that left retries on (reference: serve
    retries actor-death failures at the handle layer)."""

    def __init__(self, router: Router, rid: str, ref, span_info=None,
                 retry: Optional[tuple] = None):
        import weakref

        self._router = router
        self._rid = rid
        self._slot = _RouteSlot(router, rid, span_info)
        self._ref = ref
        self._retry = retry  # (method_name, args, kwargs, max_retries)
        self._failed: set = set()   # replica ids to avoid on re-dispatch
        self._attempts = 0          # the budget: ATTEMPTS, not unique rids
        weakref.finalize(self, self._slot.complete, False)

    def _complete(self):
        self._slot.complete()

    def _reroute(self) -> None:
        """Re-dispatch this request excluding every replica it died on.
        The original call's child TraceContext (span_info[0]) is
        re-attached around the dispatch so the retried execution's spans
        land in the same trace — result() may run on a thread with no
        ambient context at all."""
        import contextlib
        import weakref

        from ray_tpu.obs import context as trace_context

        method_name, args, kwargs, _ = self._retry
        span_info = self._slot._span_info
        self._slot.complete(record_span=False)
        ctx = (
            trace_context.use(span_info[0]) if span_info is not None
            else contextlib.nullcontext()
        )
        with ctx:
            rid, ref = self._router.dispatch(
                method_name, args, kwargs, False, exclude=set(self._failed)
            )
        self._rid = rid
        self._ref = ref
        self._slot = _RouteSlot(self._router, rid, span_info)
        weakref.finalize(self, self._slot.complete, False)

    def result(self, timeout_s: Optional[float] = None) -> Any:
        import time

        import ray_tpu

        # ONE overall deadline across failover attempts: the caller's
        # timeout bounds the call, not each retry individually
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        while True:
            remaining = (
                None if deadline is None
                else max(0.001, deadline - time.monotonic())
            )
            try:
                out = ray_tpu.get(self._ref, timeout=remaining)
                self._complete()
                return out
            except BaseException as e:  # noqa: BLE001 — filtered below
                # budget counts ATTEMPTS (a set of failed rids would never
                # grow when the only replica keeps crashing — infinite loop)
                if (
                    self._retry is None
                    or self._attempts >= self._retry[3]
                    or not _is_replica_failure(e)
                ):
                    self._complete()
                    raise
                self._attempts += 1
                failed = self._rid
                self._failed.add(failed)
                self._router.report_failure(failed)
                _record_failover(
                    self._router._app, self._router._deployment, failed, e,
                    attempt=self._attempts,
                )
                self._reroute()

    def __await__(self):
        import asyncio

        def _get():
            return self.result()

        loop = asyncio.get_event_loop()
        return loop.run_in_executor(None, _get).__await__()

    def _to_object_ref(self):
        """Expose the raw ref (for composition / ray_tpu.get interop).
        Marks routing complete — the caller owns the ref from here."""
        self._complete()
        return self._ref


class DeploymentResponseGenerator:
    """Iterator over a streaming handle call."""

    def __init__(self, router: Router, rid: str, gen, span_info=None):
        import weakref

        self._slot = _RouteSlot(router, rid, span_info)
        self._gen = gen
        weakref.finalize(self, self._slot.complete, False)

    def __iter__(self):
        import ray_tpu

        try:
            for item_ref in self._gen:
                yield ray_tpu.get(item_ref)
        finally:
            self._slot.complete()

    async def __aiter__(self):
        import asyncio

        loop = asyncio.get_event_loop()
        it = iter(self)
        while True:
            try:
                item = await loop.run_in_executor(None, lambda: next(it, _SENTINEL))
            except StopIteration:
                return
            if item is _SENTINEL:
                return
            yield item


_SENTINEL = object()


def _substitute_responses(args: tuple, kwargs: dict) -> tuple[tuple, dict]:
    def sub(x):
        if isinstance(x, DeploymentResponse):
            return x._to_object_ref()
        return x

    return tuple(sub(a) for a in args), {k: sub(v) for k, v in kwargs.items()}


# One Router per (app, deployment) process-wide: every handle copy —
# including the throwaway handles created by attribute access — shares the
# same in-flight accounting, so power-of-two-choices and max_queued
# backpressure see the true load.
_ROUTERS: dict[tuple, Router] = {}
_ROUTERS_LOCK = threading.Lock()


def _shared_router(app_name: str, deployment_name: str) -> Router:
    key = (app_name, deployment_name)
    with _ROUTERS_LOCK:
        router = _ROUTERS.get(key)
        if router is None:
            from ray_tpu.serve.api import _get_controller_handle

            # max_queued_requests arrives with the first replica-set refresh
            # (and tracks redeploys) — no snapshot RPC here
            router = Router(deployment_name, app_name, _get_controller_handle())
            _ROUTERS[key] = router
        return router


def _drop_routers() -> None:
    """Called by serve.shutdown: routers hold dead controller/replica handles."""
    with _ROUTERS_LOCK:
        _ROUTERS.clear()


class DeploymentHandle:
    """Client-side handle to a deployment; cheap to copy; safe to pass into
    other deployments' constructors (model composition)."""

    def __init__(
        self,
        deployment_name: str,
        app_name: str,
        method_name: Optional[str] = None,
        streaming: bool = False,
        system_retries: int = 2,
        pin_replica: Optional[str] = None,
        prefer_replica: Optional[str] = None,
    ):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._method_name = method_name
        self._streaming = streaming
        # failover: how many times a unary call may re-dispatch after a
        # REPLICA death (user errors never retry). 0 opts a non-idempotent
        # endpoint out via .options(system_retries=0).
        self._system_retries = system_retries
        # replica pin (KV affinity): route to exactly this replica or
        # raise ReplicaPinError — pinned calls never failover-retry, the
        # state they target died with the replica
        self._pin_replica = pin_replica
        # soft prefix affinity (r17 prefix-aware routing): PREFER this
        # replica (it already holds the request's KV prefix in some
        # tier) but fall back to p2c when it is dead, suspected, or
        # overloaded — unlike a pin, a stale hint can never fail a call
        self._prefer_replica = prefer_replica

    # Handles carry no live state — the router is process-local, looked up
    # on each dispatch — so pickling is trivially safe.
    def __getstate__(self):
        return {
            "deployment_name": self.deployment_name,
            "app_name": self.app_name,
            "_method_name": self._method_name,
            "_streaming": self._streaming,
            "_system_retries": self._system_retries,
            "_pin_replica": self._pin_replica,
            "_prefer_replica": self._prefer_replica,
        }

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.__dict__.setdefault("_system_retries", 2)
        self.__dict__.setdefault("_pin_replica", None)
        self.__dict__.setdefault("_prefer_replica", None)

    def _get_router(self) -> Router:
        return _shared_router(self.app_name, self.deployment_name)

    def options(
        self,
        *,
        method_name: Optional[str] = None,
        stream: Optional[bool] = None,
        system_retries: Optional[int] = None,
        pin_replica: Optional[str] = None,
        prefer_replica: Optional[str] = None,
        use_new_handle_api: bool = True,  # accepted for reference parity
    ) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name,
            self.app_name,
            method_name if method_name is not None else self._method_name,
            stream if stream is not None else self._streaming,
            self._system_retries if system_retries is None else system_retries,
            pin_replica if pin_replica is not None else self._pin_replica,
            prefer_replica if prefer_replica is not None else self._prefer_replica,
        )

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def remote(self, *args, **kwargs):
        args, kwargs = _substitute_responses(args, kwargs)
        router = self._get_router()
        from ray_tpu.obs import context as trace_context

        parent = trace_context.current()
        span_info = None
        if parent is not None:
            # dispatch under a child context: the actor envelope the router
            # submits captures it, so the replica's spans nest under this
            # call's serve.request span (recorded when the response is
            # consumed — _RouteSlot.complete)
            import time

            child = parent.child()
            span_info = (child, parent.span_id, time.time(), {
                "app": self.app_name,
                "deployment": self.deployment_name,
                "method": self._method_name or "__call__",
            })
            with trace_context.use(child):
                rid, ref = router.dispatch(
                    self._method_name, args, kwargs, self._streaming,
                    pin=self._pin_replica, prefer=self._prefer_replica,
                )
        else:
            rid, ref = router.dispatch(
                self._method_name, args, kwargs, self._streaming,
                pin=self._pin_replica, prefer=self._prefer_replica,
            )
        if self._streaming:
            # streaming calls never auto-retry: items may already have
            # been consumed (not idempotent to replay)
            return DeploymentResponseGenerator(router, rid, ref, span_info)
        # pinned calls never failover-retry either: the replica-resident
        # state they target (an imported KV sequence) died with the pin
        retry = (
            (self._method_name, args, kwargs, self._system_retries)
            if self._system_retries > 0 and self._pin_replica is None else None
        )
        return DeploymentResponse(router, rid, ref, span_info, retry=retry)
