"""Serve public API: @deployment, bind, run, status, shutdown.

Reference analog: python/ray/serve/api.py (serve.run :591, @serve.deployment,
serve.start, serve.status, serve.delete) and deployment graph binding
(Deployment.bind → Application). The controller is a detached named actor
(CONTROLLER_NAME), found/created on demand — same singleton pattern as the
reference's get_or_create controller path (_private/api.py).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, replace
from typing import Any, Callable, Optional, Union

from ray_tpu.serve.config import (
    AutoscalingConfig,
    DeploymentConfig,
    HTTPOptions,
    ReplicaConfig,
)
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.handle import DeploymentHandle

_lock = threading.Lock()
_controller_handle = None
_proxy = None


# ---------------------------------------------------------------------------
# deployment + application graph
# ---------------------------------------------------------------------------


@dataclass
class Deployment:
    """The decorated, configurable unit (reference: serve.Deployment)."""

    func_or_class: Union[type, Callable]
    name: str
    deployment_config: DeploymentConfig
    num_cpus: float = 1.0
    num_tpus: float = 0.0
    resources: Optional[dict] = None

    def options(self, **kwargs) -> "Deployment":
        dc_fields = {
            "num_replicas",
            "max_ongoing_requests",
            "max_queued_requests",
            "user_config",
            "autoscaling_config",
            "health_check_period_s",
            "health_check_timeout_s",
            "graceful_shutdown_timeout_s",
            "role",
        }
        dc_updates = {k: v for k, v in kwargs.items() if k in dc_fields}
        rest = {k: v for k, v in kwargs.items() if k not in dc_fields}
        actor_opts = rest.pop("ray_actor_options", None)
        if isinstance(dc_updates.get("autoscaling_config"), dict):
            dc_updates["autoscaling_config"] = AutoscalingConfig(
                **dc_updates["autoscaling_config"]
            )
        if dc_updates.get("num_replicas") == "auto":
            dc_updates["num_replicas"] = 1
            dc_updates.setdefault(
                "autoscaling_config", AutoscalingConfig(min_replicas=1, max_replicas=100)
            )
        new = replace(self, deployment_config=replace(self.deployment_config, **dc_updates))
        if actor_opts:
            new.num_cpus = actor_opts.get("num_cpus", new.num_cpus)
            new.num_tpus = actor_opts.get("num_tpus", new.num_tpus)
            new.resources = actor_opts.get("resources", new.resources)
        for k, v in rest.items():
            if not hasattr(new, k):
                raise TypeError(f"unknown deployment option {k!r}")
            setattr(new, k, v)
        return new

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __call__(self, *a, **k):
        raise TypeError(
            f"deployment {self.name} cannot be called directly; deploy it with "
            f"serve.run(dep.bind(...)) and call the returned handle"
        )


class Application:
    """A bound deployment node; init args may contain other Applications
    (composition DAG, reference: serve built-app graph)."""

    def __init__(self, deployment: Deployment, args: tuple, kwargs: dict):
        self._deployment = deployment
        self._args = args
        self._kwargs = kwargs


def deployment(
    _func_or_class=None,
    *,
    name: Optional[str] = None,
    num_replicas: Union[int, str, None] = None,
    max_ongoing_requests: int = 100,
    max_queued_requests: int = -1,
    user_config: Any = None,
    autoscaling_config: Union[AutoscalingConfig, dict, None] = None,
    health_check_period_s: float = 2.0,
    health_check_timeout_s: float = 30.0,
    graceful_shutdown_timeout_s: float = 10.0,
    role: str = "",
    ray_actor_options: Optional[dict] = None,
):
    """@serve.deployment decorator."""

    def build(target) -> Deployment:
        nonlocal autoscaling_config, num_replicas
        if isinstance(autoscaling_config, dict):
            autoscaling_config = AutoscalingConfig(**autoscaling_config)
        if num_replicas == "auto":
            num_replicas = 1
            if autoscaling_config is None:
                autoscaling_config = AutoscalingConfig(min_replicas=1, max_replicas=100)
        dcfg = DeploymentConfig(
            num_replicas=int(num_replicas or 1),
            max_ongoing_requests=max_ongoing_requests,
            max_queued_requests=max_queued_requests,
            user_config=user_config,
            autoscaling_config=autoscaling_config,
            health_check_period_s=health_check_period_s,
            health_check_timeout_s=health_check_timeout_s,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
            role=role,
        )
        opts = ray_actor_options or {}
        return Deployment(
            func_or_class=target,
            name=name or target.__name__,
            deployment_config=dcfg,
            num_cpus=opts.get("num_cpus", 1.0),
            num_tpus=opts.get("num_tpus", 0.0),
            resources=opts.get("resources"),
        )

    if _func_or_class is not None:
        return build(_func_or_class)
    return build


# ---------------------------------------------------------------------------
# controller / proxy lifecycle
# ---------------------------------------------------------------------------


def _get_controller_handle():
    global _controller_handle
    import ray_tpu

    with _lock:
        if _controller_handle is not None:
            from ray_tpu.core.actor_runtime import ActorState

            if _controller_handle.state != ActorState.DEAD:
                return _controller_handle
            _controller_handle = None
        try:
            _controller_handle = ray_tpu.get_actor(CONTROLLER_NAME)
        except ValueError:
            _controller_handle = (
                ray_tpu.remote(ServeController)
                .options(name=CONTROLLER_NAME, lifetime="detached", num_cpus=0)
                .remote()
            )
        return _controller_handle


def start(http_options: Optional[HTTPOptions] = None, **kwargs) -> None:
    """Start the Serve control plane + HTTP proxy (reference: serve.start)."""
    global _proxy
    if http_options is None:
        http_options = HTTPOptions(**kwargs) if kwargs else HTTPOptions()
    controller = _get_controller_handle()
    with _lock:
        if _proxy is None:
            from ray_tpu.serve.proxy import HTTPProxy

            _proxy = HTTPProxy(http_options.host, http_options.port, controller)


_rpc_ingress = None
_grpc_ingress = None


def _get_or_create_ingress(kind: str, factory, host: str, port: int):
    """Singleton-per-kind ingress with rebind-conflict detection:
    silently returning an ingress on a DIFFERENT address than requested
    would strand external clients on a dead port."""
    global _rpc_ingress, _grpc_ingress
    controller = _get_controller_handle()
    with _lock:
        current = _grpc_ingress if kind == "grpc" else _rpc_ingress
        if current is None:
            current = factory(host, port, controller)
            if kind == "grpc":
                _grpc_ingress = current
            else:
                _rpc_ingress = current
        elif (host, port) != ("127.0.0.1", 0) and (
            current.addr[0] != host
            or (port != 0 and current.addr[1] != port)
        ):
            raise RuntimeError(
                f"{kind} ingress already bound at {current.addr}; "
                f"cannot rebind to ({host}, {port}) — serve.shutdown() first"
            )
        return current


def start_grpc_ingress(host: str = "127.0.0.1", port: int = 0):
    """Start the standards-based gRPC front door (reference: Serve's
    gRPCProxy) — any generated client stub works; deployments exchange
    serialized protobuf bytes. Returns the ingress with its `.addr`."""
    from ray_tpu.serve.grpc_ingress import GrpcIngress

    return _get_or_create_ingress("grpc", GrpcIngress, host, port)


def start_rpc_ingress(host: str = "127.0.0.1", port: int = 0):
    """Start the binary RPC front door next to (or instead of) HTTP (the
    framed-TCP sibling of the gRPC ingress); returns the ingress with
    its bound `.addr`."""
    from ray_tpu.serve.rpc_ingress import RpcIngress

    return _get_or_create_ingress("rpc", RpcIngress, host, port)


def _collect_deployments(app: Application):
    """Walk the bound-argument DAG; return ({name: (Deployment, args, kwargs)},
    ingress_name) with nested Applications replaced by handle placeholders."""
    seen: dict[str, tuple] = {}

    def visit(node: Application) -> "_HandlePlaceholder":
        dep = node._deployment
        args = tuple(visit(a) if isinstance(a, Application) else a for a in node._args)
        kwargs = {
            k: visit(v) if isinstance(v, Application) else v
            for k, v in node._kwargs.items()
        }
        if dep.name in seen and seen[dep.name][0].func_or_class is not dep.func_or_class:
            raise ValueError(f"duplicate deployment name {dep.name!r} in application")
        seen[dep.name] = (dep, args, kwargs)
        return _HandlePlaceholder(dep.name)

    ingress = visit(app).name
    return seen, ingress


@dataclass
class _HandlePlaceholder:
    name: str


def _materialize(value, app_name: str):
    if isinstance(value, _HandlePlaceholder):
        return DeploymentHandle(value.name, app_name)
    return value


def run(
    target: Application,
    *,
    name: str = "default",
    route_prefix: Optional[str] = "/",
    blocking: bool = False,
    _start_proxy: bool = True,
    wait_for_ingress_timeout_s: float = 60.0,
) -> DeploymentHandle:
    """Deploy an application; returns a handle to its ingress deployment."""
    import ray_tpu

    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise TypeError(f"serve.run expects an Application, got {type(target)}")

    controller = _get_controller_handle()
    if _start_proxy and route_prefix is not None:
        start()

    deployments, ingress = _collect_deployments(target)
    payload = []
    for dep_name, (dep, args, kwargs) in deployments.items():
        import inspect

        is_function = not inspect.isclass(dep.func_or_class)
        args = tuple(_materialize(a, name) for a in args)
        kwargs = {k: _materialize(v, name) for k, v in kwargs.items()}
        rcfg = ReplicaConfig(
            callable_factory=dep.func_or_class,
            init_args=args,
            init_kwargs=kwargs,
            num_cpus=dep.num_cpus,
            num_tpus=dep.num_tpus,
            resources=dep.resources or {},
            is_function=is_function,
        )
        payload.append((dep_name, dep.deployment_config, rcfg))

    ray_tpu.get(
        controller.deploy_application.remote(name, route_prefix, ingress, payload)
    )
    _wait_healthy(controller, name, wait_for_ingress_timeout_s)
    handle = DeploymentHandle(ingress, name)
    if blocking:
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            pass
    return handle


def _wait_healthy(controller, app_name: str, timeout_s: float) -> None:
    import ray_tpu

    deadline = time.time() + timeout_s
    while time.time() < deadline:
        st = ray_tpu.get(controller.status.remote())
        app = st["applications"].get(app_name)
        if app and app["status"] == "RUNNING":
            return
        if app and app["status"] in ("DEPLOY_FAILED", "UNHEALTHY"):
            raise RuntimeError(f"application {app_name} failed to deploy: {app}")
        time.sleep(0.05)
    raise TimeoutError(f"application {app_name} not healthy after {timeout_s}s")


# ---------------------------------------------------------------------------
# status / handles / teardown
# ---------------------------------------------------------------------------


def status() -> dict:
    import ray_tpu

    return ray_tpu.get(_get_controller_handle().status.remote())


def get_app_handle(name: str = "default") -> DeploymentHandle:
    import ray_tpu

    controller = _get_controller_handle()
    ingress = ray_tpu.get(controller.get_ingress.remote(name))
    if ingress is None:
        raise ValueError(f"application {name!r} not found")
    return DeploymentHandle(ingress, name)


def get_deployment_handle(deployment_name: str, app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def delete(name: str) -> None:
    import ray_tpu
    from ray_tpu.serve import handle as _handle_mod

    ray_tpu.get(_get_controller_handle().delete_application.remote(name))
    with _handle_mod._ROUTERS_LOCK:
        for key in [k for k in _handle_mod._ROUTERS if k[0] == name]:
            del _handle_mod._ROUTERS[key]


def shutdown() -> None:
    global _controller_handle, _proxy, _rpc_ingress, _grpc_ingress
    import ray_tpu
    from ray_tpu.serve.handle import _drop_routers

    _drop_routers()
    with _lock:
        proxy, _proxy = _proxy, None
        ingress, _rpc_ingress = _rpc_ingress, None
        gingress, _grpc_ingress = _grpc_ingress, None
        controller, _controller_handle = _controller_handle, None
    if gingress is not None:
        gingress.shutdown()
    if ingress is not None:
        ingress.shutdown()
    if proxy is not None:
        proxy.shutdown()
    if controller is not None:
        try:
            ray_tpu.get(controller.shutdown.remote(), timeout=10)
            ray_tpu.kill(controller)
        except Exception:
            pass
