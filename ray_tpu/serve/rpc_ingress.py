"""RPC ingress: the binary second front door next to the HTTP proxy.

Reference analog: Serve's gRPC proxy (python/ray/serve/_private/
proxy.py gRPCProxy — user-defined service methods routed to
deployments). This framework's wire substrate is the framed TCP RPC
plane (cluster/rpc.py), so the binary ingress speaks that instead of
protoc services: one `call` method carrying (app, method, args,
kwargs), routed through the same controller route table and
DeploymentHandles the HTTP proxy uses. Python clients get structured
arguments/results with no JSON round-trip.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import ray_tpu
from ray_tpu.cluster.rpc import RpcClient, RpcServer
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.serve.rpc_ingress")


class RpcIngress:
    """Framed-RPC front end. Handlers run on the RPC server's executor
    threads; each blocks on the deployment's reply like an HTTP worker."""

    def __init__(self, host: str, port: int, controller_handle):
        self._controller = controller_handle
        from ray_tpu.serve.routes import AppResolver

        self._resolver = AppResolver(controller_handle, error_cls=ValueError)
        self.rpc = RpcServer(self, host=host, port=port)
        self.addr = self.rpc.start()

    # -- RPC surface ----------------------------------------------------------

    def rpc_call(self, payload, peer):
        """{app?, method?, args?, kwargs?} -> deployment result (pickled
        by the wire). `method` targets a named method on the ingress
        deployment; omitted = its __call__."""
        app, ingress = self._resolver.resolve(payload.get("app"))
        handle = self._resolver.handle_for(app, ingress)
        if payload.get("method"):
            handle = getattr(handle, payload["method"])
        response = handle.remote(*payload.get("args", ()),
                                 **payload.get("kwargs", {}))
        return response.result(timeout_s=payload.get("timeout", 120.0))

    def rpc_routes(self, payload, peer):
        return dict(self._resolver.route_cache.get())

    def shutdown(self) -> None:
        self.rpc.stop()


def rpc_ingress_call(addr: tuple, *args, app: Optional[str] = None,
                     method: Optional[str] = None, timeout: float = 120.0,
                     **kwargs):
    """Client helper: one structured call against an RpcIngress."""
    c = RpcClient(addr[0], addr[1], timeout=timeout + 10).connect()
    try:
        return c.call(
            "call",
            {"app": app, "method": method, "args": args, "kwargs": kwargs,
             "timeout": timeout},
        )
    finally:
        c.close()
