"""Disaggregated prefill/decode serving as serve deployments.

The multi-deployment variant of ``ray_tpu.llm.disagg``: prefill and
decode engines live in SEPARATE deployments (role-tagged "prefill" /
"decode"), scaled and health-checked independently by the serve
controller, with the ingress reusing the r09 serving machinery:

 * new requests go to the prefill pool through an ordinary
   power-of-two-choices dispatch (replica death before the handoff is
   retried by the handle's system_retries failover — the prefill call
   is idempotent: same completion id, nothing delivered yet);
 * the exported KV ships over the app's ``KVConnector`` to a decode
   replica the ingress picks with queue-depth + prefix-cache-hit-rate
   awareness (decode stats are polled with a short TTL);
 * the decode-side wait is PINNED (``options(pin_replica=...)``): an
   imported KV sequence lives on exactly one replica, so a dead pin
   surfaces as ``ReplicaPinError`` and the ingress re-prefills under a
   bounded budget instead of silently landing on a replica without the
   state;
 * admission control (llm/admission.py) sheds load at the ingress
   exactly as the colocated OpenAI app does.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Optional

from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.serve.disagg")


def _is_transfer_failure(exc: BaseException) -> bool:
    """Is this (or anything in its cause chain) a transfer-plane loss —
    a lost/corrupt handoff or a dead pinned replica? Matched by type
    name because user exceptions cross the actor plane wrapped in
    TaskError/ClusterTaskError with the original as `.cause`."""
    names = {"KVTransferError", "ReplicaPinError"}
    seen: set = set()
    stack: list = [exc]
    while stack:
        e = stack.pop()
        if e is None or id(e) in seen:
            continue
        seen.add(id(e))
        if type(e).__name__ in names:
            return True
        stack.append(getattr(e, "cause", None))
        stack.append(e.__cause__)
    return False


def _build_engine(llm_config):
    from ray_tpu.llm.engine import LLMEngine

    return LLMEngine(
        llm_config.engine, params=llm_config.params, seed=llm_config.seed
    )


def _make_connector(kind: str, namespace: str):
    from ray_tpu.llm.disagg.connector import make_connector

    if kind in ("inproc", "in_process", "inprocess", "device"):
        # namespaced planes: replicas of one app share endpoints through
        # the process-global maps without cross-delivering another app's
        return make_connector(kind, namespace=namespace)
    return make_connector(kind)


class PrefillServer:
    """One prefill-role engine per replica: admission + prefill + first
    token, then export + send — it never decodes."""

    def __init__(self, llm_config, connector_kind: str = "inproc",
                 namespace: str = "disagg"):
        import uuid as _uuid

        from ray_tpu import obs  # noqa: F401 — engine tracing rides requests

        self.engine = _build_engine(llm_config)
        self.engine.model_tag = f"{llm_config.model_id}-prefill"
        # prefix-aware routing (llm/kvtier): publish this replica's
        # resident chains into the app's shared prefix index under a
        # stable key the ingress maps back to a replica id
        self._index_key = f"prefill-{_uuid.uuid4().hex[:12]}"
        if self.engine.kvtier is not None:
            from ray_tpu.llm.kvfetch import (
                LocalFetchClient,
                get_local_fetch_registry,
            )
            from ray_tpu.llm.kvtier import get_local_index

            self.engine.kvtier.attach_index(
                get_local_index(namespace), engine_key=self._index_key
            )
            # cross-engine resurrection (llm/kvfetch): this replica both
            # SERVES its spilled blocks to the app's other replicas and
            # PULLS prefixes the ingress routed here for fetch
            registry = get_local_fetch_registry(namespace)
            registry.register(self._index_key, self.engine.kvtier)
            if self.engine.kvfetch is not None:
                self.engine.kvfetch.attach(LocalFetchClient(registry))
        self.connector = _make_connector(connector_kind, namespace)
        # device plane: export device-resident + device-sealed, so the
        # pages go gather -> device_put without ever staging through
        # host RAM (and without a host-CRC + device-reseal round trip)
        self._export_dev = getattr(self.connector, "name", "") == "device"
        self._lock = threading.Lock()
        self._outs: dict[str, Any] = {}
        self._handoffs: dict[str, Any] = {}

    def prefill(self, prompt_ids: list, sampling: dict, request_id: str,
                target: Any) -> dict:
        """Prefill one request and ship its KV to ``target``. Returns the
        first sampled token(s); raises KVTransferError when the handoff
        was lost (the ingress re-prefills, same completion id)."""
        from ray_tpu import obs
        from ray_tpu.llm.sampling import SamplingParams

        sp = SamplingParams(**sampling)
        with self._lock:
            self.engine.add_request(
                list(prompt_ids), sp, request_id=request_id,
                trace=obs.current(),
            )
        deadline = time.time() + 120.0
        while True:
            with self._lock:
                if request_id in self._outs:
                    break
                if self.engine.has_unfinished():
                    for out in self.engine.step():
                        self._outs[out.request_id] = out
                    # everything still running was just admitted: export
                    # before it ever decodes (a concurrent call picks its
                    # own export up from the shared dict)
                    for r in list(self.engine.running):
                        self._handoffs[r.request_id] = self.engine.export_request(
                            r.request_id, keep_on_device=self._export_dev
                        )
                elif request_id not in self._outs:
                    raise RuntimeError(
                        f"request {request_id!r} vanished from the prefill "
                        "engine without an output"
                    )
            if time.time() > deadline:
                raise TimeoutError(f"prefill of {request_id!r} timed out")
        with self._lock:
            out = self._outs.pop(request_id)
            handoff = self._handoffs.pop(request_id, None)
        if handoff is not None:
            # KVTransferError propagates to the ingress as a user
            # exception — deliberate: transfer loss is NOT a replica
            # death, the handle must not blind-retry it (the ingress owns
            # the budgeted re-prefill)
            self.connector.send(target, handoff)
        return {
            "token_ids": list(out.output_token_ids),
            "finished": out.finished,
            "finish_reason": out.finish_reason,
            "handed_off": handoff is not None,
        }

    def index_key(self) -> str:
        """This replica's key in the app's prefix index (the ingress
        reverse-maps lookup winners onto replica ids)."""
        return self._index_key

    def stats(self) -> dict:
        with self._lock:
            return {**self.engine.stats(), "connector": self.connector.stats()}

    def shutdown(self):
        self.connector.close()


class DecodeServer:
    """One decode-role engine per replica: imports handoffs from its
    connector target and runs pure decode rounds on a loop thread."""

    POLL_S = 0.02

    def __init__(self, llm_config, connector_kind: str = "inproc",
                 namespace: str = "disagg"):
        self.engine = _build_engine(llm_config)
        self.engine.model_tag = f"{llm_config.model_id}-decode"
        self.connector = _make_connector(connector_kind, namespace)
        self._target_id = f"decode-{uuid.uuid4().hex[:12]}"
        if self.engine.kvtier is not None:
            from ray_tpu.llm.kvfetch import (
                LocalFetchClient,
                get_local_fetch_registry,
            )
            from ray_tpu.llm.kvtier import get_local_index

            self.engine.kvtier.attach_index(
                get_local_index(namespace), engine_key=self._target_id
            )
            registry = get_local_fetch_registry(namespace)
            registry.register(self._target_id, self.engine.kvtier)
            if self.engine.kvfetch is not None:
                self.engine.kvfetch.attach(LocalFetchClient(registry))
        if getattr(self.connector, "name", "") == "device":
            # device plane: pin the endpoint to this engine's KV-cache
            # device so the sender's device_put IS the final hop
            self._target = self.connector.register_target(
                self._target_id, device=self.engine.kv_cache_device()
            )
        else:
            self._target = self.connector.register_target(self._target_id)
        self._lock = threading.Lock()
        self._done: dict[str, Any] = {}     # rid -> final RequestOutput
        self._failed: dict[str, str] = {}   # rid -> reason (corrupt/no room)
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name=f"disagg-decode-{self._target_id}",
            daemon=True,
        )
        self._thread.start()

    # -- control plane --------------------------------------------------------

    def kv_target(self) -> Any:
        """Opaque connector address of THIS replica (the ingress maps
        replica_id -> target for pinned KV-affinity dispatch)."""
        return self._target

    def index_key(self) -> str:
        """This replica's key in the app's prefix index (same id the
        kv target rides, so one poll covers both)."""
        return self._target_id

    def stats(self) -> dict:
        with self._lock:
            s = self.engine.stats()
        s["connector"] = self.connector.stats()
        return s

    # -- data plane -----------------------------------------------------------

    def _loop(self) -> None:
        from ray_tpu.llm.kv_cache import NoFreeBlocksError

        while not self._stop:
            with self._lock:
                busy = self.engine.has_unfinished()
            h = self.connector.recv(
                self._target_id, timeout_s=0.001 if busy else self.POLL_S
            )
            if h is not None:
                if not h.verify():
                    with self._lock:
                        self._failed[h.request_id] = "checksum failed (corrupt)"
                else:
                    try:
                        with self._lock:
                            self.engine.import_handoff(h)
                    except NoFreeBlocksError:
                        with self._lock:
                            self._failed[h.request_id] = "no KV room"
                    except Exception as e:  # noqa: BLE001
                        with self._lock:
                            self._failed[h.request_id] = f"import failed: {e}"
            if busy:
                try:
                    with self._lock:
                        for out in self.engine.step():
                            if out.finished:
                                self._done[out.request_id] = out
                except Exception:  # noqa: BLE001
                    logger.exception("decode engine step failed; recovering")
                    try:
                        with self._lock:
                            self.engine.recover()
                    except Exception:  # noqa: BLE001
                        logger.exception("decode engine unrecoverable")

    def wait_finish(self, request_id: str, timeout_s: float = 120.0) -> dict:
        """Block until ``request_id`` (imported via a prior handoff to
        this replica) finishes; bounded — a handoff that never arrived
        fails the wait instead of hanging the ingress."""
        from ray_tpu.llm.disagg.connector import KVTransferError

        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._lock:
                out = self._done.pop(request_id, None)
                fail = self._failed.pop(request_id, None)
            if fail is not None:
                raise KVTransferError(
                    f"handoff of {request_id!r} unusable on this replica: {fail}"
                )
            if out is not None:
                return {
                    "token_ids": list(out.output_token_ids),
                    "finish_reason": out.finish_reason,
                    "num_cached_tokens": out.num_cached_tokens,
                }
            time.sleep(0.005)
        raise KVTransferError(
            f"request {request_id!r} did not finish on this replica within "
            f"{timeout_s}s (handoff lost?)"
        )

    def shutdown(self):
        self._stop = True
        self._thread.join(timeout=5)
        self.connector.close()


class DisaggIngress:
    """OpenAI-style ingress over the two pools (reference shape:
    llm/openai_api.LLMServer, minus streaming)."""

    STATS_TTL_S = 0.5
    MAX_RETRIES = 2

    def __init__(self, llm_config, prefill_handle, decode_handle,
                 namespace: str = "disagg", index=None):
        from ray_tpu.llm.admission import AdmissionConfig, AdmissionController
        from ray_tpu.llm.openai_api import ByteTokenizer

        self.config = llm_config
        self.tokenizer = llm_config.tokenizer or ByteTokenizer(
            llm_config.engine.model.vocab_size
        )
        self.prefill_handle = prefill_handle
        self.decode_handle = decode_handle
        # prefix-aware routing (llm/kvtier): the index the pool replicas
        # publish their resident chains into. In-process serving shares
        # the app-namespaced LocalPrefixIndex; a cluster deployment
        # injects a GcsPrefixIndex — either way a dark/stale index makes
        # the picks below degrade to the existing queue-depth/p2c ladder
        # (no hang, no wrong-replica pin).
        self.index = index
        if self.index is None and llm_config.engine.kvtier is not None:
            from ray_tpu.llm.kvtier import get_local_index

            self.index = get_local_index(namespace)
        acfg = llm_config.admission
        if isinstance(acfg, dict):
            acfg = AdmissionConfig(**acfg)
        self.admission = AdmissionController(
            acfg or AdmissionConfig(), model_tag=llm_config.model_id
        )
        self._lock = threading.Lock()
        self._targets: dict[str, Any] = {}   # decode replica_id -> kv target
        self._stats: dict[str, dict] = {}    # decode replica_id -> stats
        self._stats_at = 0.0
        # replica_id -> prefix-index key, per pool (polled with the same
        # TTL discipline as targets/stats)
        self._decode_keys: dict[str, str] = {}
        self._prefill_keys: dict[str, str] = {}
        self._prefill_at = 0.0
        self.num_reprefills = 0
        self.num_prefix_routed = 0

    # -- decode-pool discovery + pick -----------------------------------------

    def _decode_router(self):
        return self.decode_handle._get_router()

    def _refresh_decode(self) -> list[str]:
        """Poll decode replica ids, kv targets, and stats with a TTL."""
        rids = self._decode_router().replica_ids()
        now = time.time()
        with self._lock:
            fresh = now - self._stats_at < self.STATS_TTL_S
            known = set(self._targets)
        if fresh and known >= set(rids):
            return rids
        # fire every poll before collecting any: the waits overlap, so a
        # hung (not yet evicted) replica costs one timeout window, not
        # one per replica, on the request path that called us
        target_futs, stat_futs, key_futs = {}, {}, {}
        for rid in rids:
            try:
                if rid not in known:
                    target_futs[rid] = self.decode_handle.options(
                        pin_replica=rid
                    ).kv_target.remote()
                    if self.index is not None:
                        key_futs[rid] = self.decode_handle.options(
                            pin_replica=rid
                        ).index_key.remote()
                stat_futs[rid] = self.decode_handle.options(
                    pin_replica=rid
                ).stats.remote()
            except Exception:  # noqa: BLE001 — replica racing startup/death
                continue
        for rid, fut in target_futs.items():
            try:
                target = fut.result(timeout_s=10)
            except Exception:  # noqa: BLE001
                continue
            with self._lock:
                self._targets[rid] = target
        for rid, fut in key_futs.items():
            try:
                key = fut.result(timeout_s=10)
            except Exception:  # noqa: BLE001
                continue
            with self._lock:
                self._decode_keys[rid] = key
        stats = {}
        for rid, fut in stat_futs.items():
            try:
                stats[rid] = fut.result(timeout_s=10)
            except Exception:  # noqa: BLE001
                continue
        with self._lock:
            self._stats = stats
            self._stats_at = now
            dead = set(self._targets) - set(rids)
            for rid in dead:
                self._targets.pop(rid, None)
                self._decode_keys.pop(rid, None)
        return rids

    def _refresh_prefill(self) -> dict:
        """Prefill replica_id -> index key, with the same TTL (the
        prefer hint needs a replica id, the index speaks in keys)."""
        if self.index is None:
            return {}
        now = time.time()
        with self._lock:
            if now - self._prefill_at < self.STATS_TTL_S and self._prefill_keys:
                return dict(self._prefill_keys)
        try:
            rids = self.prefill_handle._get_router().replica_ids()
        except Exception:  # noqa: BLE001 — controller refresh racing death
            return {}
        futs = {}
        with self._lock:
            known = dict(self._prefill_keys)
        for rid in rids:
            if rid in known:
                continue
            try:
                futs[rid] = self.prefill_handle.options(
                    pin_replica=rid
                ).index_key.remote()
            except Exception:  # noqa: BLE001
                continue
        for rid, fut in futs.items():
            try:
                known[rid] = fut.result(timeout_s=10)
            except Exception:  # noqa: BLE001
                continue
        with self._lock:
            self._prefill_keys = {r: k for r, k in known.items() if r in rids}
            self._prefill_at = now
            return dict(self._prefill_keys)

    def _prefix_hashes(self, prompt_ids: list) -> list:
        from ray_tpu.llm.kvtier import chain_hashes

        return chain_hashes(prompt_ids, self.config.engine.block_size)

    def _index_lookup(self, prompt_ids: list):
        """ONE index lookup per attempt, shared by the prefill prefer
        and the decode pick (hashing the prompt and hitting the index —
        two RPCs on the GCS-backed path — must not happen twice per
        request). None = index off/dark = no information."""
        if self.index is None:
            return None
        try:
            return self.index.lookup(self._prefix_hashes(prompt_ids))
        except Exception:  # noqa: BLE001 — dark index = no information
            return None

    def _fetch_weight(self) -> float:
        """The r18 fetch-cost discount the app's engines route with
        (0.0 when the tiered cache or prefetch plane is off)."""
        kvt = self.config.engine.kvtier
        if kvt is None or not kvt.prefetch:
            return 0.0
        return float(kvt.fetch_weight)

    def _prefer_prefill(self, lookup):
        """Prefill replica already holding this prompt's longest
        tier-discounted prefix, or None (-> plain p2c). Deliberately
        NO fetch-cost discount here: the ingress has no prefill queue
        depths (every candidate scores depth 0), so a fetch score would
        tie EVERY replica and pin all no-holder traffic to one fixed
        id — None keeps those requests on the router's p2c ladder, and
        whichever replica wins still prefetches via its own engine."""
        if lookup is None:
            return None
        from ray_tpu.llm.kvtier.index import best_prefix_replica

        keys = self._refresh_prefill()
        if not keys:
            return None
        got = best_prefix_replica(
            lookup, {rid: 0 for rid in keys}, key_of=keys,
        )
        if got is not None:
            self.num_prefix_routed += 1
        return got

    def _pick_decode(self, lookup=None) -> tuple[str, Any]:
        """Prefix-aware first (the replica already holding this
        prompt's longest tier-discounted prefix, via the app's prefix
        index, bounded by depth slack), then the existing ladder —
        queue depth with prefix-cache hit rate as tiebreak — whenever
        the index is dark, stale, or holds nothing for this prompt.
        The serve-mode mirror of DisaggOrchestrator._pick_decode."""
        from ray_tpu.serve.router import ReplicaPinError

        rids = self._refresh_decode()
        with self._lock:
            scored = []
            depths = {}
            for rid in rids:
                if rid not in self._targets:
                    continue
                s = self._stats.get(rid, {})
                depth = s.get("num_waiting", 0) + s.get("num_running", 0)
                hit = s.get("prefix_cache", {}).get("hit_rate", 0.0)
                depths[rid] = depth
                scored.append((depth, -hit, rid))
            if not scored:
                raise ReplicaPinError("no decode replicas available")
            key_of = dict(self._decode_keys)
        if lookup is not None and key_of:
            from ray_tpu.llm.kvtier.index import best_prefix_replica

            got = best_prefix_replica(lookup, depths, key_of=key_of,
                                      fetch_weight=self._fetch_weight())
            if got is not None:
                with self._lock:
                    target = self._targets.get(got)
                if target is not None:
                    self.num_prefix_routed += 1
                    return got, target
        with self._lock:
            _, _, rid = min(scored)
            return rid, self._targets[rid]

    # -- request path ---------------------------------------------------------

    def _sampling_from_body(self, body: dict) -> dict:
        return {
            "max_tokens": int(body.get("max_tokens", 64)),
            "temperature": float(body.get("temperature", 1.0)),
            "top_k": int(body.get("top_k", 0)),
            "top_p": float(body.get("top_p", 1.0)),
            "seed": body.get("seed"),
            "logprobs": bool(body.get("logprobs", False)),
        }

    def _generate(self, prompt_ids: list, sampling: dict, rid: str) -> dict:
        """prefill -> handoff -> pinned decode wait, with the bounded
        re-prefill ladder on any transfer-plane loss."""
        last: Optional[BaseException] = None
        for attempt in range(self.MAX_RETRIES + 1):
            if attempt > 0:
                self.num_reprefills += 1
            try:
                lookup = self._index_lookup(prompt_ids)
                decode_rid, target = self._pick_decode(lookup)
                prefill_handle = self.prefill_handle
                prefer = self._prefer_prefill(lookup)
                if prefer is not None:
                    # soft prefix affinity: the handle's router honors it
                    # only while the replica is healthy and not overloaded
                    prefill_handle = prefill_handle.options(
                        prefer_replica=prefer
                    )
                pre = prefill_handle.prefill.remote(
                    prompt_ids, sampling, rid, target
                ).result(timeout_s=180)
                if pre["finished"]:
                    return {
                        "token_ids": pre["token_ids"],
                        "finish_reason": pre["finish_reason"],
                    }
                return self.decode_handle.options(
                    pin_replica=decode_rid
                ).wait_finish.remote(rid).result(timeout_s=180)
            except BaseException as e:  # noqa: BLE001 — filtered below
                if not _is_transfer_failure(e):
                    raise
                # lost handoff / dead pinned replica: re-prefill under the
                # budget — the completion id is stable and nothing beyond
                # the prefill token was delivered, so the retry is
                # idempotent from the client's point of view
                last = e
                logger.warning(
                    "disagg request %s attempt %d failed (%s); re-prefilling",
                    rid, attempt + 1, e,
                )
                with self._lock:
                    self._stats_at = 0.0  # force re-discovery
        raise RuntimeError(
            f"request {rid!r}: transfer plane failed "
            f"{self.MAX_RETRIES + 1} times"
        ) from last

    async def completions(self, body: dict) -> dict:
        import uuid as _uuid

        from ray_tpu import obs

        rej = self._admission_check()
        if rej is not None:
            return rej
        prompts = body.get("prompt", "")
        if not isinstance(prompts, list):
            prompts = [prompts]
        sampling = self._sampling_from_body(body)
        rid = f"cmpl-{_uuid.uuid4().hex[:24]}"
        with obs.span("api.completions", attrs={
            "request_id": rid,
            "model": body.get("model", self.config.model_id),
            "endpoint": "/v1/completions", "disagg": True,
        }) as ctx:
            import asyncio

            loop = asyncio.get_running_loop()
            results = []
            n_prompt = 0
            for i, p in enumerate(prompts):
                ids = self.tokenizer.encode(str(p))
                n_prompt += len(ids)
                erid = rid if len(prompts) == 1 else f"{rid}-{i}"
                out = await loop.run_in_executor(
                    None, self._generate, ids, sampling, erid
                )
                toks = out["token_ids"]
                if toks and toks[-1] == self.config.engine.eos_token_id:
                    toks = toks[:-1]
                results.append((self.tokenizer.decode(toks), toks,
                                out["finish_reason"]))
        n_out = sum(len(t) for _, t, _ in results)
        return {
            "id": rid,
            "object": "text_completion",
            "created": int(time.time()),
            "model": body.get("model", self.config.model_id),
            "trace_id": ctx.trace_id,
            "choices": [
                {"index": i, "text": text, "finish_reason": reason,
                 "logprobs": None}
                for i, (text, _t, reason) in enumerate(results)
            ],
            "usage": {
                "prompt_tokens": n_prompt,
                "completion_tokens": n_out,
                "total_tokens": n_prompt + n_out,
            },
        }

    def _admission_check(self) -> Optional[dict]:
        with self._lock:
            stats = dict(self._stats)
        waiting = sum(s.get("num_waiting", 0) for s in stats.values())
        running = sum(s.get("num_running", 0) for s in stats.values())
        return self.admission.check(num_waiting=waiting, num_running=running)

    def stats(self) -> dict:
        self._refresh_decode()
        with self._lock:
            return {
                "model_id": self.config.model_id,
                "mode": "disagg",
                "decode": dict(self._stats),
                "admission": self.admission.stats(),
                "reprefills": self.num_reprefills,
                "prefix_routed": self.num_prefix_routed,
            }

    async def __call__(self, request):
        path, method = request.path, request.method
        if path.rstrip("/") == "/v1/stats" and method == "GET":
            return self.stats()
        if path.rstrip("/") == "/v1/completions" and method == "POST":
            return await self.completions(request.json())
        return {"error": {"message": f"no route {method} {path}", "code": 404}}


def build_disagg_openai_app(
    llm_config,
    *,
    num_prefill: int = 1,
    num_decode: int = 1,
    connector: str = "inproc",
    name: str = "llm-disagg",
    route_prefix: str = "/disagg",
):
    """Deploy prefill pool + decode pool + ingress; returns the ingress
    handle. Pools are role-tagged so serve.status and replica listings
    show the topology."""
    from ray_tpu import serve

    prefill_dep = serve.deployment(
        PrefillServer,
        name=f"Prefill:{llm_config.model_id}",
        num_replicas=num_prefill,
        role="prefill",
    )
    decode_dep = serve.deployment(
        DecodeServer,
        name=f"Decode:{llm_config.model_id}",
        num_replicas=num_decode,
        role="decode",
    )
    ingress_dep = serve.deployment(
        DisaggIngress,
        name=f"DisaggIngress:{llm_config.model_id}",
        num_replicas=1,
    )
    app = ingress_dep.bind(
        llm_config,
        prefill_dep.bind(llm_config, connector, name),
        decode_dep.bind(llm_config, connector, name),
        name,
    )
    return serve.run(app, name=name, route_prefix=route_prefix)
