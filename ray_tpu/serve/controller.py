"""Serve controller: the singleton control-plane actor.

Reference analog: python/ray/serve/_private/controller.py:84
(ServeController) + deployment_state.py (DeploymentStateManager:2329,
DeploymentState:1248) + application_state.py + autoscaling_state.py.
Collapsed into one reconciliation loop: desired state (configs set by
deploy) vs actual state (live replica actors), converged every tick —
replica start/stop, health checks, user_config pushes, and queue-depth
autoscaling all happen in the loop, exactly like the reference's
control loop, minus the cross-process long-poll machinery.
"""

from __future__ import annotations

import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Optional

from ray_tpu.serve.config import (
    ApplicationStatus,
    DeploymentConfig,
    DeploymentStatus,
    ReplicaConfig,
    ReplicaState,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.serve.controller")

CONTROLLER_NAME = "SERVE_CONTROLLER"


def replica_gauges() -> dict:
    """Role-tagged replica gauges: the pool view the telemetry plane
    rolls up by DeploymentConfig.role (prefill/decode pools under
    disaggregated serving) for `ray_tpu status` and the autoscaler."""
    from ray_tpu.obs.telemetry import cluster_gauge

    return {
        "running": cluster_gauge(
            "serve_replicas_running",
            description="serve replicas in RUNNING state per deployment "
            "(role-tagged for pool rollups)",
            tag_keys=("app", "deployment", "role"),
        ),
        "target": cluster_gauge(
            "serve_replicas_target",
            description="serve replica target per deployment "
            "(role-tagged for pool rollups)",
            tag_keys=("app", "deployment", "role"),
        ),
    }


def register_metrics() -> None:
    """scripts/check_metrics.py hook."""
    replica_gauges()


@dataclass
class _ReplicaInfo:
    replica_id: str
    handle: Any  # ActorHandle of Replica
    state: str = ReplicaState.STARTING
    consecutive_health_failures: int = 0
    last_ongoing: float = 0.0


@dataclass
class _DeploymentState:
    name: str
    app_name: str
    deployment_config: DeploymentConfig
    replica_config: ReplicaConfig
    version: int = 0  # bumped when the running replica set changes
    code_version: int = 0  # bumped when replica_config changes (full restart)
    target_replicas: int = 1
    # pool-level override set by the r20 PoolAutoscaler (set_pool_target);
    # None = deployment owns its target (num_replicas / autoscaling_config)
    pool_target: Optional[int] = None
    replicas: list = field(default_factory=list)  # list[_ReplicaInfo]
    status: str = DeploymentStatus.UPDATING
    # consecutive replica deaths with no replica ever reaching RUNNING at
    # this code_version → deploy failure, not a transient fault
    consecutive_start_failures: int = 0
    ever_running: bool = False
    last_error: str = ""
    _counter: int = 0
    # sliding window of (t, total_ongoing) for autoscaling
    metrics_window: list = field(default_factory=list)
    last_scale_up: float = 0.0
    last_scale_down: float = 0.0


@dataclass
class _AppState:
    name: str
    route_prefix: Optional[str]
    ingress: str  # ingress deployment name
    deployments: dict = field(default_factory=dict)  # name -> _DeploymentState
    status: str = ApplicationStatus.DEPLOYING


class ServeController:
    """Run as a detached named actor; reconcile loop in a daemon thread."""

    def __init__(self, reconcile_interval_s: float = 0.1):
        self._lock = threading.RLock()
        self._apps: dict[str, _AppState] = {}
        self._interval = reconcile_interval_s
        self._shutdown = threading.Event()
        self._thread = threading.Thread(
            target=self._reconcile_loop, name="serve-reconcile", daemon=True
        )
        self._thread.start()

    # -- deploy / delete ------------------------------------------------------

    def deploy_application(
        self,
        name: str,
        route_prefix: Optional[str],
        ingress: str,
        deployments: list,
    ) -> None:
        """deployments: list of (dep_name, DeploymentConfig, ReplicaConfig)."""
        with self._lock:
            app = self._apps.get(name)
            if app is None:
                app = _AppState(name=name, route_prefix=route_prefix, ingress=ingress)
                self._apps[name] = app
            app.route_prefix = route_prefix
            app.ingress = ingress
            app.status = ApplicationStatus.DEPLOYING
            new_names = set()
            for dep_name, dcfg, rcfg in deployments:
                new_names.add(dep_name)
                ds = app.deployments.get(dep_name)
                if ds is None:
                    ds = _DeploymentState(
                        name=dep_name,
                        app_name=name,
                        deployment_config=dcfg,
                        replica_config=rcfg,
                    )
                    ds.target_replicas = dcfg.target_initial_replicas()
                    app.deployments[dep_name] = ds
                else:
                    self._apply_update(ds, dcfg, rcfg)
            # deployments removed from the app spec are torn down
            for stale in set(app.deployments) - new_names:
                for r in app.deployments[stale].replicas:
                    self._stop_replica(app.deployments[stale], r)
                self._retract_replica_gauges(app.deployments[stale])
                del app.deployments[stale]

    def _apply_update(
        self, ds: _DeploymentState, dcfg: DeploymentConfig, rcfg: ReplicaConfig
    ) -> None:
        """In-place update semantics (reference deployment_state's
        lightweight-update path): user_config-only changes push
        reconfigure(); replica_config changes roll all replicas."""
        old = ds.deployment_config
        code_changed = (
            rcfg.callable_factory is not ds.replica_config.callable_factory
            or rcfg.init_args != ds.replica_config.init_args
            or rcfg.init_kwargs != ds.replica_config.init_kwargs
        )
        user_config_changed = dcfg.user_config != old.user_config
        ds.deployment_config = dcfg
        ds.replica_config = rcfg
        if dcfg.autoscaling_config is None:
            ds.target_replicas = dcfg.num_replicas
        else:
            ac = dcfg.autoscaling_config
            ds.target_replicas = max(
                ac.min_replicas, min(ac.max_replicas, max(ds.target_replicas, 1))
            )
        if code_changed:
            ds.code_version += 1
            ds.status = DeploymentStatus.UPDATING
            ds.consecutive_start_failures = 0
            ds.ever_running = False
            ds.last_error = ""
            for r in list(ds.replicas):
                self._stop_replica(ds, r)
        elif user_config_changed and dcfg.user_config is not None:
            for r in ds.replicas:
                try:
                    r.handle.reconfigure.remote(dcfg.user_config)
                except Exception:
                    logger.exception("reconfigure push failed")

    def delete_application(self, name: str) -> None:
        with self._lock:
            app = self._apps.get(name)
            if app is None:
                return
            app.status = ApplicationStatus.DELETING
            for ds in app.deployments.values():
                ds.target_replicas = 0
                for r in list(ds.replicas):
                    self._stop_replica(ds, r)
                self._retract_replica_gauges(ds)
            del self._apps[name]

    def shutdown(self) -> None:
        with self._lock:
            for name in list(self._apps):
                self.delete_application(name)
        self._shutdown.set()

    # -- queries (router / proxy / status surface) ---------------------------

    def get_running_replicas(self, app_name: str, dep_name: str) -> dict:
        with self._lock:
            ds = self._get_ds(app_name, dep_name)
            if ds is None:
                return {"version": -1, "replicas": [], "max_queued_requests": -1}
            reps = [
                (
                    r.replica_id,
                    r.handle,
                    ds.deployment_config.max_ongoing_requests,
                )
                for r in ds.replicas
                if r.state == ReplicaState.RUNNING
            ]
            return {
                "version": ds.version,
                "replicas": reps,
                # shipped with every refresh so routers track config updates
                "max_queued_requests": ds.deployment_config.max_queued_requests,
                # pool role ("prefill"/"decode" under disaggregated
                # serving): pool-aware clients tell deployments apart
                # without a second control-plane call
                "role": ds.deployment_config.role,
            }

    def get_ingress(self, app_name: str):
        with self._lock:
            app = self._apps.get(app_name)
            return app.ingress if app else None

    def get_app_route(self, app_name: str) -> Optional[str]:
        with self._lock:
            app = self._apps.get(app_name)
            return app.route_prefix if app else None

    def list_routes(self) -> dict:
        """route_prefix -> (app_name, ingress_deployment)."""
        with self._lock:
            return {
                app.route_prefix: (app.name, app.ingress)
                for app in self._apps.values()
                if app.route_prefix is not None
            }

    def status(self) -> dict:
        with self._lock:
            out = {"applications": {}}
            for app in self._apps.values():
                deps = {}
                for ds in app.deployments.values():
                    deps[ds.name] = {
                        "status": ds.status,
                        "message": ds.last_error,
                        "replica_states": {
                            s: sum(1 for r in ds.replicas if r.state == s)
                            for s in (ReplicaState.STARTING, ReplicaState.RUNNING)
                        },
                        "target_replicas": ds.target_replicas,
                    }
                    if ds.deployment_config.role:
                        deps[ds.name]["role"] = ds.deployment_config.role
                out["applications"][app.name] = {
                    "status": app.status,
                    "route_prefix": app.route_prefix,
                    "deployments": deps,
                }
            return out

    def _get_ds(self, app_name: str, dep_name: str) -> Optional[_DeploymentState]:
        app = self._apps.get(app_name)
        if app is None:
            return None
        return app.deployments.get(dep_name)

    # -- reconciliation -------------------------------------------------------

    def _reconcile_loop(self) -> None:
        last_health = 0.0
        while not self._shutdown.is_set():
            try:
                now = time.time()
                with self._lock:
                    for app in list(self._apps.values()):
                        for ds in app.deployments.values():
                            self._reconcile_deployment(ds, now)
                            self._export_replica_gauges(ds)
                        self._update_app_status(app)
                if now - last_health > 1.0:
                    last_health = now
                    self._poll_replicas()
            except Exception:
                logger.exception("reconcile tick failed")
            self._shutdown.wait(self._interval)

    def _reconcile_deployment(self, ds: _DeploymentState, now: float) -> None:
        self._autoscale(ds, now)
        running = [r for r in ds.replicas if r.state == ReplicaState.RUNNING]
        starting = [r for r in ds.replicas if r.state == ReplicaState.STARTING]
        n_live = len(running) + len(starting)
        if ds.consecutive_start_failures >= 3 and not ds.ever_running:
            # every replica of this code version died before serving: a
            # broken deployment, not a transient fault — stop crash-looping
            ds.status = DeploymentStatus.UNHEALTHY
            return
        for _ in range(ds.target_replicas - n_live):
            self._start_replica(ds)
        if n_live > ds.target_replicas:
            # scale down: prefer stopping STARTING, then least-loaded RUNNING
            excess = n_live - ds.target_replicas
            victims = (starting + sorted(running, key=lambda r: r.last_ongoing))[:excess]
            for r in victims:
                self._stop_replica(ds, r)
        # STARTING → RUNNING promotion happens in _poll_replicas (health ping)
        if ds.target_replicas > 0 and running and not starting:
            ds.status = DeploymentStatus.HEALTHY
        elif starting:
            ds.status = DeploymentStatus.UPDATING

    def _export_replica_gauges(self, ds: _DeploymentState) -> None:
        """Publish running/target replica counts into the process metrics
        registry (telemetry-plane pool rollups key off the role tag)."""
        try:
            g = replica_gauges()
            tags = {
                "app": ds.app_name,
                "deployment": ds.name,
                "role": ds.deployment_config.role,
            }
            running = sum(
                1 for r in ds.replicas if r.state == ReplicaState.RUNNING
            )
            g["running"].set(running, tags=tags)
            g["target"].set(ds.target_replicas, tags=tags)
        except Exception:  # noqa: BLE001 — observability must not break serve
            pass

    def _retract_replica_gauges(self, ds: _DeploymentState) -> None:
        """Remove a deleted deployment's gauge series — a gauge that is
        merely no longer updated keeps its last value in the registry and
        every telemetry snapshot would keep shipping phantom replicas."""
        try:
            g = replica_gauges()
            tags = {
                "app": ds.app_name,
                "deployment": ds.name,
                "role": ds.deployment_config.role,
            }
            g["running"].remove_series(tags=tags)
            g["target"].remove_series(tags=tags)
        except Exception:  # noqa: BLE001 — observability must not break serve
            pass

    def _update_app_status(self, app: _AppState) -> None:
        statuses = {ds.status for ds in app.deployments.values()}
        if statuses <= {DeploymentStatus.HEALTHY}:
            app.status = ApplicationStatus.RUNNING
        elif DeploymentStatus.UNHEALTHY in statuses:
            never_served = any(
                ds.status == DeploymentStatus.UNHEALTHY and not ds.ever_running
                for ds in app.deployments.values()
            )
            app.status = (
                ApplicationStatus.DEPLOY_FAILED
                if never_served and app.status == ApplicationStatus.DEPLOYING
                else ApplicationStatus.UNHEALTHY
            )

    def _start_replica(self, ds: _DeploymentState) -> None:
        import ray_tpu
        from ray_tpu.serve.replica import Replica

        ds._counter += 1
        rid = f"{ds.app_name}#{ds.name}#{ds.code_version}.{ds._counter}"
        rcfg = ds.replica_config
        try:
            handle = (
                ray_tpu.remote(Replica)
                .options(
                    num_cpus=rcfg.num_cpus,
                    num_tpus=rcfg.num_tpus,
                    resources=dict(rcfg.resources),
                    # high cap: the replica gates data-plane concurrency
                    # itself so control-plane calls never queue behind it
                    max_concurrency=10_000,
                    name=f"SERVE_REPLICA::{rid}",
                )
                .remote(
                    ds.name,
                    ds.app_name,
                    rcfg.callable_factory,
                    rcfg.init_args,
                    rcfg.init_kwargs,
                    rcfg.is_function,
                    ds.deployment_config.user_config,
                    ds.deployment_config.max_ongoing_requests,
                )
            )
        except Exception:
            logger.exception("replica start failed for %s", rid)
            ds.status = DeploymentStatus.UNHEALTHY
            return
        ds.replicas.append(_ReplicaInfo(replica_id=rid, handle=handle))

    def _stop_replica(self, ds: _DeploymentState, r: _ReplicaInfo) -> None:
        import ray_tpu

        r.state = ReplicaState.STOPPING
        ds.replicas.remove(r)
        ds.version += 1

        timeout = ds.deployment_config.graceful_shutdown_timeout_s

        def _drain():
            try:
                ray_tpu.get(r.handle.prepare_shutdown.remote(timeout), timeout=timeout + 1)
            except Exception:
                pass
            try:
                ray_tpu.kill(r.handle)
            except Exception:
                pass

        threading.Thread(target=_drain, daemon=True).start()

    def kill_replica(self, app_name: str, dep_name: Optional[str] = None,
                     replica_id: Optional[str] = None) -> Optional[str]:
        """Fault injection (chaos KILL_REPLICA): crash one replica WITHOUT
        any bookkeeping — exactly what a preempted replica looks like. The
        health sweep notices the corpse (ActorDiedError on ping), evicts
        it, and starts a replacement; routers fail over in the meantime.
        Returns the killed replica_id, or None if nothing was running."""
        import ray_tpu
        from ray_tpu.serve.config import ReplicaState

        with self._lock:
            app = self._apps.get(app_name)
            if app is None:
                return None
            if dep_name:
                if dep_name not in app.deployments:
                    # an unknown deployment name must NOT fall back to
                    # "kill anything": a chaos test would crash the wrong
                    # deployment and assert against an unexercised path
                    return None
                deps = [app.deployments[dep_name]]
            else:
                deps = list(app.deployments.values())
            victim = None
            for ds in deps:
                pool = [r for r in ds.replicas if r.state == ReplicaState.RUNNING] \
                    or list(ds.replicas)
                for r in pool:
                    if replica_id in (None, r.replica_id):
                        victim = r
                        break
                if victim is not None:
                    break
        if victim is None:
            return None
        logger.warning("chaos: killing replica %s", victim.replica_id)
        try:
            ray_tpu.kill(victim.handle)
        except Exception:
            logger.exception("chaos replica kill failed")
            return None
        return victim.replica_id

    def _poll_replicas(self) -> None:
        """Health-check + metrics sweep (outside the lock for the RPCs).
        Fan out all pings first, then collect — one wedged replica must not
        stall checks for every other deployment."""
        import ray_tpu

        with self._lock:
            targets = [
                (ds, r)
                for app in self._apps.values()
                for ds in app.deployments.values()
                for r in list(ds.replicas)
            ]
        pings = []
        for ds, r in targets:
            try:
                pings.append(r.handle.ping.remote())
            except Exception:
                pings.append(None)
        for (ds, r), ref in zip(targets, pings):
            try:
                if ref is None:
                    raise RuntimeError("ping dispatch failed")
                metrics = ray_tpu.get(
                    ref,
                    timeout=ds.deployment_config.health_check_timeout_s,
                )
                with self._lock:
                    r.consecutive_health_failures = 0
                    r.last_ongoing = metrics["num_ongoing_requests"]
                    if r.state == ReplicaState.STARTING:
                        r.state = ReplicaState.RUNNING
                        ds.version += 1
                        ds.ever_running = True
                        ds.consecutive_start_failures = 0
            except Exception as e:
                from ray_tpu.core.errors import ActorDiedError

                with self._lock:
                    r.consecutive_health_failures += 1
                    # a dead actor (e.g. constructor raised) needs no 3-strike
                    # grace — replace (or give up) immediately
                    dead = isinstance(e, ActorDiedError)
                    if dead or r.consecutive_health_failures >= 3:
                        logger.warning(
                            "replica %s %s; replacing",
                            r.replica_id,
                            "died" if dead else "failed health checks",
                        )
                        if r in ds.replicas:
                            ds.replicas.remove(r)
                            ds.version += 1
                        if r.state == ReplicaState.STARTING and not ds.ever_running:
                            ds.consecutive_start_failures += 1
                            ds.last_error = f"{type(e).__name__}: {e}"
                        try:
                            ray_tpu.kill(r.handle)
                        except Exception:
                            pass
        # fold fresh ongoing counts into autoscaling windows
        with self._lock:
            now = time.time()
            for app in self._apps.values():
                for ds in app.deployments.values():
                    total = sum(r.last_ongoing for r in ds.replicas)
                    ds.metrics_window.append((now, total))

    def _autoscale(self, ds: _DeploymentState, now: float) -> None:
        ac = ds.deployment_config.autoscaling_config
        if ac is None:
            # pool-level override (r20 PoolAutoscaler) wins over the
            # static num_replicas; scale-down still routes through the
            # reconcile loop's graceful drain
            ds.target_replicas = (
                ds.pool_target
                if ds.pool_target is not None
                else ds.deployment_config.num_replicas
            )
            return
        ds.metrics_window = [
            (t, v) for t, v in ds.metrics_window if now - t <= ac.look_back_period_s
        ]
        if not ds.metrics_window:
            return
        avg_total = sum(v for _, v in ds.metrics_window) / len(ds.metrics_window)
        current = max(1, len(ds.replicas))
        desired = ac.desired_replicas(avg_total, current)
        if desired > ds.target_replicas and now - ds.last_scale_up >= ac.upscale_delay_s:
            ds.target_replicas = desired
            ds.last_scale_up = now
            ds.status = DeploymentStatus.UPSCALING
        elif (
            desired < ds.target_replicas
            and now - ds.last_scale_down >= ac.downscale_delay_s
        ):
            ds.target_replicas = desired
            ds.last_scale_down = now
            ds.status = DeploymentStatus.DOWNSCALING

    # -- pool-level actuator surface (r20 PoolAutoscaler) ---------------------

    def set_pool_target(self, role: str, target: int) -> dict:
        """Set the desired replica count on every deployment tagged with
        ``role`` (prefill/decode pools under disaggregated serving).

        Scale-down routes through the reconcile loop's graceful drain
        (_stop_replica: prepare_shutdown before kill) — never a hard
        kill. Deployments carrying their own autoscaling_config are
        skipped: their queue-depth loop owns the target, and two writers
        would fight."""
        target = max(0, int(target))
        touched: list[str] = []
        with self._lock:
            for app in self._apps.values():
                for ds in app.deployments.values():
                    if (ds.deployment_config.role or "") != role:
                        continue
                    if ds.deployment_config.autoscaling_config is not None:
                        continue
                    ds.pool_target = target
                    touched.append(f"{ds.app_name}/{ds.name}")
        return {"role": role, "target": target, "deployments": touched}

    def pool_state(self, role: Optional[str] = None) -> dict:
        """Role-keyed replica counts — the actuator's read-back view
        (the telemetry plane's pool_rollups is the cluster-wide one)."""
        out: dict = {}
        with self._lock:
            for app in self._apps.values():
                for ds in app.deployments.values():
                    r = ds.deployment_config.role or "(none)"
                    if role is not None and r != role:
                        continue
                    pool = out.setdefault(r, {
                        "replicas_running": 0, "replicas_target": 0,
                        "deployments": [],
                    })
                    pool["replicas_running"] += sum(
                        1 for ri in ds.replicas
                        if ri.state == ReplicaState.RUNNING
                    )
                    pool["replicas_target"] += ds.target_replicas
                    pool["deployments"].append(f"{ds.app_name}/{ds.name}")
        return out
