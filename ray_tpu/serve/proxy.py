"""HTTP proxy: ingress for Serve applications.

Reference analog: python/ray/serve/_private/proxy.py (ProxyActor:1129,
HTTPProxy:752) — uvicorn/starlette there; aiohttp here (what this image
ships). One proxy per host, routing by longest route-prefix match to the
app's ingress deployment handle, mirroring the reference's proxy router
(_private/proxy_router.py).
"""

from __future__ import annotations

import asyncio
import json
import threading
from dataclasses import dataclass, field
from typing import Any, Optional

from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.serve.proxy")


@dataclass
class Request:
    """What an HTTP ingress callable receives (stand-in for the reference's
    starlette.Request; carries the same essentials)."""

    method: str
    path: str  # path below the route prefix
    query: dict
    headers: dict
    body: bytes = b""
    route_prefix: str = "/"

    def json(self) -> Any:
        return json.loads(self.body or b"null")

    @property
    def text(self) -> str:
        return self.body.decode()


class HTTPProxy:
    """aiohttp server in a daemon thread with its own event loop."""

    def __init__(self, host: str, port: int, controller_handle):
        self._host = host
        self._port = port
        self._controller = controller_handle
        self._handles: dict[str, Any] = {}  # app_name -> DeploymentHandle
        from ray_tpu.serve.routes import RouteTableCache

        self._route_cache = RouteTableCache(controller_handle)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve_forever, name="serve-http-proxy", daemon=True
        )
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError(f"HTTP proxy failed to bind {host}:{port} within 10s")

    @property
    def port(self) -> int:
        return self._port

    def _match(self, path: str):
        """Longest-prefix route match (shared cache: serve/routes.py)."""
        return self._route_cache.match(path)

    def _get_handle(self, app: str, ingress: str):
        h = self._handles.get(app)
        if h is None or h.deployment_name != ingress:
            from ray_tpu.serve.handle import DeploymentHandle

            h = DeploymentHandle(ingress, app)
            self._handles[app] = h
        return h

    async def _handle(self, request):
        from aiohttp import web

        path = request.path
        if path == "/-/healthz":
            return web.Response(text="success")
        if path == "/-/routes":
            # controller RPC off-loop, like the data path
            routes = await asyncio.get_running_loop().run_in_executor(
                None, self._route_cache.get
            )
            return web.json_response({p: a for p, (a, _) in routes.items()})
        match = await asyncio.get_running_loop().run_in_executor(
            None, self._match, path
        )
        if match is None:
            return web.Response(status=404, text=f"no route for {path}")
        norm, prefix, app, ingress = match
        sub_path = path[len(norm):] if norm != "/" else path
        body = await request.read()
        req = Request(
            method=request.method,
            path=sub_path or "/",
            query=dict(request.query),
            headers=dict(request.headers),
            body=body,
            route_prefix=prefix,
        )
        handle = self._get_handle(app, ingress)
        loop = asyncio.get_running_loop()
        try:
            result = await loop.run_in_executor(
                None, lambda: handle.remote(req).result(timeout_s=300)
            )
        except Exception as e:  # surface replica errors as 500s
            logger.exception("request to %s failed", path)
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")
        return self._to_response(result)

    @staticmethod
    def _to_response(result):
        from aiohttp import web

        if isinstance(result, web.Response):
            return result
        if isinstance(result, bytes):
            return web.Response(body=result)
        if isinstance(result, str):
            return web.Response(text=result)
        if isinstance(result, dict):
            # OpenAI-style error payloads carry their HTTP status in
            # error.code; admission rejections additionally carry a
            # retry_after hint the client reads from the Retry-After
            # header (429 overload / 503 draining)
            err = result.get("error")
            if isinstance(err, dict) and isinstance(err.get("code"), int) \
                    and 400 <= err["code"] < 600:
                headers = {}
                try:
                    from ray_tpu.llm.admission import retry_after_header

                    ra = retry_after_header(result)
                    if ra is not None:
                        headers["Retry-After"] = ra
                except Exception:  # noqa: BLE001
                    pass
                return web.json_response(
                    result, status=err["code"], headers=headers
                )
        return web.json_response(result)

    def _serve_forever(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        app = web.Application(client_max_size=1 << 30)
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app, access_log=None)

        async def _run():
            await runner.setup()
            site = web.TCPSite(runner, self._host, self._port)
            await site.start()
            self._started.set()
            while not self._stop.is_set():
                await asyncio.sleep(0.1)
            await runner.cleanup()

        try:
            loop.run_until_complete(_run())
        except Exception:
            logger.exception("proxy loop crashed")
        finally:
            loop.close()

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
