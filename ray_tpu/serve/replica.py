"""Serve replica: the actor that hosts one copy of a user callable.

Reference analog: python/ray/serve/_private/replica.py (ReplicaActor
:883, handle_request/handle_request_streaming :988-1016). Differences
from the reference are deliberate: replicas here are async actors in the
host process (threads), so sync user callables are pushed onto an
executor to keep the replica's event loop responsive for health checks
and metrics queries.
"""

from __future__ import annotations

import asyncio
import inspect
import time
from typing import Any, Optional

from ray_tpu.chaos import harness as _chaos


class Replica:
    """User-code host. Instantiated as an async actor (max_concurrency
    bounds in-flight requests, matching max_ongoing_requests)."""

    def __init__(
        self,
        deployment_name: str,
        app_name: str,
        callable_factory,
        init_args: tuple,
        init_kwargs: dict,
        is_function: bool,
        user_config: Any = None,
        max_ongoing_requests: int = 100,
    ):
        self._deployment_name = deployment_name
        self._app_name = app_name
        self._is_function = is_function
        # Data-plane concurrency cap. The actor itself runs with a high
        # max_concurrency so control-plane calls (metrics, health checks,
        # reconfigure) never queue behind user requests.
        self._request_sem = asyncio.Semaphore(max(1, max_ongoing_requests))
        self._num_ongoing = 0
        self._num_processed = 0
        self._started_at = time.time()
        cls_or_fn = callable_factory
        if is_function:
            self._callable = cls_or_fn
        else:
            self._callable = cls_or_fn(*init_args, **init_kwargs)
        if user_config is not None:
            self._apply_user_config(user_config)

    # -- control-plane surface ------------------------------------------------

    def _apply_user_config(self, user_config) -> None:
        reconfigure = getattr(self._callable, "reconfigure", None)
        if reconfigure is None:
            raise ValueError(
                f"user_config was set but {type(self._callable).__name__} "
                f"defines no reconfigure() method"
            )
        reconfigure(user_config)

    async def reconfigure(self, user_config) -> None:
        self._apply_user_config(user_config)

    async def check_health(self) -> bool:
        check = getattr(self._callable, "check_health", None)
        if check is not None:
            out = check()
            if inspect.isawaitable(out):
                await out
        return True

    async def metrics(self) -> dict:
        return {
            "num_ongoing_requests": self._num_ongoing,
            "num_processed": self._num_processed,
            "uptime_s": time.time() - self._started_at,
        }

    async def ping(self) -> dict:
        """Controller health sweep: run the user's check_health hook, then
        report metrics. A raising hook fails the ping → replica replaced."""
        await self.check_health()
        return await self.metrics()

    async def prepare_shutdown(self, timeout_s: float) -> None:
        """Drain in-flight requests, then run the user's cleanup hook
        (graceful_shutdown_timeout_s)."""
        deadline = time.time() + timeout_s
        while self._num_ongoing > 0 and time.time() < deadline:
            await asyncio.sleep(0.01)
        if self._is_function:
            return
        # Prefer a dedicated shutdown() hook. For a user __del__, DROP our
        # reference instead of calling it — CPython refcounting then invokes
        # __del__ exactly once, here, rather than twice (explicit call + GC).
        hook = getattr(self._callable, "shutdown", None)
        if hook is not None and callable(hook):
            try:
                out = hook()
                if inspect.isawaitable(out):
                    await out
            except Exception:
                pass  # cleanup errors must not block teardown
        elif getattr(type(self._callable), "__del__", None) is not None:
            self._callable = None

    # -- data plane -----------------------------------------------------------

    def _resolve_target(self, method_name: Optional[str]):
        if self._is_function:
            return self._callable
        if method_name:
            target = getattr(self._callable, method_name, None)
            if target is None or not callable(target):
                raise AttributeError(
                    f"deployment {self._deployment_name} has no method {method_name!r}"
                )
            return target
        target = getattr(self._callable, "__call__", None)
        if target is None:
            raise AttributeError(
                f"deployment {self._deployment_name} is not callable; "
                f"specify a method name"
            )
        return target

    @staticmethod
    async def _resolve_refs(args, kwargs):
        """Upstream DeploymentResponses arrive as ObjectRefs nested in the
        args tuple (core only resolves top-level task args); fetch them here,
        off-loop so pending upstream calls don't block the replica."""
        from ray_tpu.core.ref import ObjectRef

        if not any(isinstance(a, ObjectRef) for a in args) and not any(
            isinstance(v, ObjectRef) for v in kwargs.values()
        ):
            return args, kwargs
        import ray_tpu

        loop = asyncio.get_running_loop()

        async def get(ref):
            return await loop.run_in_executor(None, lambda: ray_tpu.get(ref))

        args = tuple(
            [(await get(a)) if isinstance(a, ObjectRef) else a for a in args]
        )
        kwargs = {
            k: (await get(v)) if isinstance(v, ObjectRef) else v
            for k, v in kwargs.items()
        }
        return args, kwargs

    def _request_span(self, method_name: Optional[str]):
        """`serve.replica` span (queue-wait + execution) when the call
        arrived under a trace; no-op context otherwise so untraced traffic
        doesn't fill the flight recorder."""
        import contextlib

        from ray_tpu import obs

        if obs.current() is None:
            return contextlib.nullcontext()
        return obs.span("serve.replica", attrs={
            "deployment": self._deployment_name,
            "app": self._app_name,
            "method": method_name or "__call__",
        })

    def _chaos_hook(self, method_name: Optional[str]) -> None:
        """KILL_REPLICA injection: the request dies the way it would if
        this replica's process/actor crashed mid-call — callers see a
        system failure (ReplicaCrashed), the router's failover path
        retries elsewhere, the controller's health sweep replaces us."""
        if _chaos.ACTIVE is None:
            return
        for _f in _chaos.fire(
            "serve.replica", kinds=(_chaos.KILL_REPLICA,),
            deployment=self._deployment_name, app=self._app_name,
            method=method_name or "__call__",
        ):
            if _f.kind == _chaos.KILL_REPLICA:
                raise _chaos.ReplicaCrashed(
                    f"chaos: replica of {self._app_name}/"
                    f"{self._deployment_name} crashed mid-request"
                )

    async def handle_request(self, method_name: Optional[str], args, kwargs):
        """Unary request path. _num_ongoing counts queued + executing — the
        autoscaling signal wants in-replica load, not just active slots."""
        self._num_ongoing += 1
        try:
            async with self._request_sem:
                self._chaos_hook(method_name)
                with self._request_span(method_name):
                    args, kwargs = await self._resolve_refs(args, kwargs)
                    target = self._resolve_target(method_name)
                    if inspect.iscoroutinefunction(target):
                        return await target(*args, **kwargs)
                    # Sync callable: run off-loop so long computations don't
                    # starve the replica's event loop. copy_context ships
                    # the trace contextvar to the executor thread.
                    import contextvars

                    loop = asyncio.get_running_loop()
                    call_ctx = contextvars.copy_context()
                    out = await loop.run_in_executor(
                        None, lambda: call_ctx.run(target, *args, **kwargs)
                    )
                    if inspect.isawaitable(out):
                        out = await out
                    return out
        finally:
            self._num_ongoing -= 1
            self._num_processed += 1

    async def handle_request_streaming(self, method_name: Optional[str], args, kwargs):
        """Streaming path: the target must return an (a)sync generator;
        items are yielded through the framework's ObjectRefGenerator."""
        self._num_ongoing += 1
        try:
            async with self._request_sem:  # same cap as the unary path
                self._chaos_hook(method_name)
                with self._request_span(method_name):
                    args, kwargs = await self._resolve_refs(args, kwargs)
                    target = self._resolve_target(method_name)
                    out = target(*args, **kwargs)
                    if inspect.isawaitable(out):
                        out = await out
                    if hasattr(out, "__aiter__"):
                        async for item in out:
                            yield item
                    elif hasattr(out, "__iter__"):
                        for item in out:
                            yield item
                    else:
                        yield out
        finally:
            self._num_ongoing -= 1
            self._num_processed += 1
