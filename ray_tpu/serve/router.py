"""Request router: picks a replica per request.

Reference analog: python/ray/serve/_private/router.py:321 +
replica_scheduler/pow_2_scheduler.py — power-of-two-choices over replica
queue lengths. This router keeps its own in-flight count per replica
(incremented on dispatch, decremented on completion) instead of the
reference's cached queue-length RPCs: all routers live in the host
process, so local counts are exact for a single router and a cheap,
contention-free approximation across several.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional


class PendingRequestQueue(Exception):
    pass


class BackpressureError(Exception):
    """max_queued_requests exceeded at the router (reference:
    serve._private.router queue-length backpressure)."""


class ReplicaPinError(Exception):
    """A pinned dispatch's target replica is gone (dead, evicted, or
    replaced). Pinning exists for replica-resident state — a KV handoff
    imported on ONE decode replica — so the router must fail loudly
    instead of silently re-homing the call onto a replica that doesn't
    hold the state (disaggregated serving re-prefills on this error)."""


class Router:
    def __init__(
        self,
        deployment_name: str,
        app_name: str,
        controller_handle,
    ):
        self._deployment = deployment_name
        self._app = app_name
        self._controller = controller_handle
        self._max_queued = -1  # refreshed with the replica set
        self._lock = threading.Lock()
        self._replicas: list = []  # list[(replica_id, ActorHandle, max_ongoing)]
        self._version = -1
        self._inflight: dict[str, int] = {}
        self._last_refresh = 0.0
        # failover suspects: replica_id -> expiry. A reported-dead replica
        # is avoided for SUSPECT_TTL_S even after a refresh re-adopts the
        # controller's (not yet updated) set — without routing forever
        # around a replica that only suffered an injected/transient crash
        self._suspect: dict[str, float] = {}

    SUSPECT_TTL_S = 2.0

    # -- replica-set maintenance ---------------------------------------------

    def _refresh(self, block: bool = False) -> None:
        """Pull the running replica set from the controller if stale.
        (The reference pushes via long-poll; a pull with a version check
        is equivalent single-host and far simpler.)"""
        import ray_tpu

        now = time.time()
        # invariant (lock-guard allowlist): this staleness fast-path reads
        # _replicas/_last_refresh WITHOUT _lock on purpose — both are
        # GIL-atomic reads, a stale value costs at most one redundant
        # refresh RPC or 0.25s of extra staleness, and taking _lock here
        # measurably serializes the dispatch fan-out (overload shedding
        # depends on concurrent arrivals; see test_overload_sheds_429)
        if not block and self._replicas and now - self._last_refresh < 0.25:
            return
        try:
            info = ray_tpu.get(
                self._controller.get_running_replicas.remote(self._app, self._deployment)
            )
        except Exception:
            # degraded-mode contract (control-plane blackout): the
            # controller/GCS being unreachable may only cost routing
            # FRESHNESS — keep serving the cached replica set and retry
            # the refresh on a later dispatch. Only an empty cache (no
            # replicas ever seen) propagates the failure.
            with self._lock:
                if self._replicas:
                    self._last_refresh = now
                    return
            raise
        with self._lock:
            self._last_refresh = now
            self._max_queued = info.get("max_queued_requests", -1)
            if info["version"] != self._version:
                self._version = info["version"]
                self._replicas = info["replicas"]
                live = {rid for rid, _, _ in self._replicas}
                self._inflight = {
                    rid: n for rid, n in self._inflight.items() if rid in live
                }

    def _wait_for_replicas(self, timeout: float = 30.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            self._refresh(block=True)
            with self._lock:
                if self._replicas:
                    return
            time.sleep(0.05)
        raise TimeoutError(
            f"no running replicas for deployment "
            f"{self._app}/{self._deployment} after {timeout}s"
        )

    def report_failure(self, rid: str) -> None:
        """Failover eviction: a dispatch to this replica hit a system
        failure (actor died / crashed mid-request). Drop it from the local
        routing set immediately — the controller's health sweep replaces
        it, but until that lands no new request should race onto the
        corpse — and force a controller refresh on the next dispatch."""
        with self._lock:
            self._replicas = [r for r in self._replicas if r[0] != rid]
            # the inflight count is NOT popped: a replica that survives a
            # transient crash comes back with its real outstanding load
            # (zeroing it would make p2c prefer the busiest replica);
            # a genuinely dead replica's counter is pruned by the refresh
            # once the controller drops it
            # TTL'd suspicion: the refresh below may re-adopt the
            # controller's set (its health sweep runs on seconds) with
            # the corpse still in it — _pick avoids suspects while an
            # alternative exists, and expiry lets a replica that only
            # suffered an injected/transient crash come back
            self._suspect[rid] = time.time() + self.SUSPECT_TTL_S
            # force the next refresh to re-adopt the controller's set even
            # at an unchanged version: a crash that didn't kill the actor
            # (injected fault, transient) leaves the controller's view
            # intact, and the evicted replica must be able to come back
            self._last_refresh = 0.0
            self._version = -1

    # -- scheduling -----------------------------------------------------------

    # prefix-affinity slack: a preferred (cache-holding) replica is only
    # honored while its in-flight count is within this many requests of
    # the least-loaded candidate — affinity must not overload one replica
    PREFER_SLACK = 4

    def _pick(self, exclude: Optional[set] = None,
              prefer: Optional[str] = None):
        """Power-of-two-choices on local in-flight counts; skips replicas at
        max_ongoing_requests when an alternative exists. ``exclude``
        (failover retries) removes replicas this request already died on —
        falling back to them only when nothing else exists. ``prefer`` is
        a SOFT affinity hint (prefix-aware routing: that replica already
        holds this request's KV prefix): honored only when the replica is
        live, un-suspected, not excluded, and not overloaded past
        PREFER_SLACK — in every other case the normal ladder decides, so
        a stale hint can never pin a request onto a corpse."""
        now = time.time()
        with self._lock:
            for rid in [r for r, t in self._suspect.items() if t <= now]:
                del self._suspect[rid]
            suspects = set(self._suspect)
            replicas = list(self._replicas)

        def _avoiding(pool):
            # preference ladder: avoid suspects AND this request's failed
            # replicas; if that empties the pool, drop only the (possibly
            # stale) suspicion — a replica THIS request died on is a hard
            # fact and must stay excluded while any alternative exists
            hard = set(exclude or ())
            best = [r for r in pool if r[0] not in suspects and r[0] not in hard]
            if best:
                return best
            unfailed = [r for r in pool if r[0] not in hard]
            return unfailed or pool

        replicas = _avoiding(replicas)
        if not replicas:
            self._wait_for_replicas()
            with self._lock:
                replicas = list(self._replicas)
            replicas = _avoiding(replicas)
        if prefer is not None and len(replicas) > 1:
            preferred = next((r for r in replicas if r[0] == prefer), None)
            if preferred is not None:
                # one consistent snapshot: the overload check compares
                # counts against each other, so they must come from the
                # same instant (unlike the p2c reads below, which compare
                # two independent heuristic samples)
                with self._lock:
                    counts = {
                        r[0]: self._inflight.get(r[0], 0) for r in replicas
                    }
                if counts.get(prefer, 0) <= min(counts.values()) + self.PREFER_SLACK:
                    return preferred
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)
        # invariant (lock-guard allowlist): p2c is a heuristic — these are
        # GIL-atomic int reads and a stale counter only skews ONE pick
        # toward the busier replica; the accounting itself (increment on
        # dispatch, decrement on completion) stays under _lock. Locking
        # here would put a hot mutex on every dispatch for zero
        # correctness gain.
        na = self._inflight.get(a[0], 0)
        nb = self._inflight.get(b[0], 0)
        return a if na <= nb else b

    def replica_ids(self, refresh: bool = True) -> list[str]:
        """Current running replica ids (pool enumeration for pool-aware
        callers, e.g. disaggregated serving discovering decode targets)."""
        if refresh:
            self._refresh()
        with self._lock:
            return [rid for rid, _, _ in self._replicas]

    def _pick_pinned(self, pin: str):
        """Hard replica pin: the request must land on `pin` (it holds
        replica-resident state) or fail with ReplicaPinError — suspects
        included, p2c skipped. One blocking refresh covers the window
        where the controller just replaced the set."""
        for attempt in range(2):
            with self._lock:
                for r in self._replicas:
                    if r[0] == pin:
                        return r
            if attempt == 0:
                self._refresh(block=True)
        raise ReplicaPinError(
            f"replica {pin!r} of {self._app}/{self._deployment} is gone; "
            "its replica-resident state died with it"
        )

    def dispatch(self, method_name: Optional[str], args, kwargs, streaming: bool,
                 exclude: Optional[set] = None, pin: Optional[str] = None,
                 prefer: Optional[str] = None):
        """Route one request; returns (replica_id, ObjectRef-or-generator).

        ``pin`` routes to exactly that replica (replica-resident state:
        a transferred KV sequence lives on ONE decode replica) or raises
        ReplicaPinError; ``prefer`` is the soft prefix-affinity variant —
        honored when healthy and not overloaded, silently ignored
        otherwise (a dark/stale prefix index degrades to plain p2c, it
        never mis-pins); otherwise power-of-two-choices picks.

        The dispatch wall-clock (refresh + pick + submit — the router's
        own contribution to request latency) lands in the
        serve_router_dispatch_seconds histogram; the trace context, when
        the caller carries one, rides the actor-task envelope the
        `.remote()` below captures, so the replica executes inside the
        request's trace."""
        t0 = time.perf_counter()
        self._refresh()
        with self._lock:
            # one consistent snapshot: inflight sum and replica count move
            # together under _lock, so backpressure prices a real state
            over_queued = self._max_queued >= 0 and sum(
                self._inflight.values()
            ) >= self._max_queued + len(self._replicas)
        if over_queued:
            raise BackpressureError(
                f"deployment {self._app}/{self._deployment}: "
                f"max_queued_requests={self._max_queued} exceeded"
            )
        if pin is not None:
            rid, handle, _max_ongoing = self._pick_pinned(pin)
        else:
            rid, handle, _max_ongoing = self._pick(exclude, prefer=prefer)
        with self._lock:
            self._inflight[rid] = self._inflight.get(rid, 0) + 1
        try:
            if streaming:
                ref = handle.handle_request_streaming.options(
                    num_returns="streaming"
                ).remote(method_name, args, kwargs)
            else:
                ref = handle.handle_request.remote(method_name, args, kwargs)
        except Exception:
            with self._lock:
                self._inflight[rid] = max(0, self._inflight.get(rid, 1) - 1)
            raise
        finally:
            from ray_tpu.obs import slo

            slo.record_dispatch(
                self._app, self._deployment, time.perf_counter() - t0
            )
        return rid, ref

    def complete(self, rid: str) -> None:
        with self._lock:
            self._inflight[rid] = max(0, self._inflight.get(rid, 1) - 1)
