"""Request router: picks a replica per request.

Reference analog: python/ray/serve/_private/router.py:321 +
replica_scheduler/pow_2_scheduler.py — power-of-two-choices over replica
queue lengths. This router keeps its own in-flight count per replica
(incremented on dispatch, decremented on completion) instead of the
reference's cached queue-length RPCs: all routers live in the host
process, so local counts are exact for a single router and a cheap,
contention-free approximation across several.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional


class PendingRequestQueue(Exception):
    pass


class BackpressureError(Exception):
    """max_queued_requests exceeded at the router (reference:
    serve._private.router queue-length backpressure)."""


class Router:
    def __init__(
        self,
        deployment_name: str,
        app_name: str,
        controller_handle,
    ):
        self._deployment = deployment_name
        self._app = app_name
        self._controller = controller_handle
        self._max_queued = -1  # refreshed with the replica set
        self._lock = threading.Lock()
        self._replicas: list = []  # list[(replica_id, ActorHandle, max_ongoing)]
        self._version = -1
        self._inflight: dict[str, int] = {}
        self._last_refresh = 0.0

    # -- replica-set maintenance ---------------------------------------------

    def _refresh(self, block: bool = False) -> None:
        """Pull the running replica set from the controller if stale.
        (The reference pushes via long-poll; a pull with a version check
        is equivalent single-host and far simpler.)"""
        import ray_tpu

        now = time.time()
        if not block and self._replicas and now - self._last_refresh < 0.25:
            return
        info = ray_tpu.get(
            self._controller.get_running_replicas.remote(self._app, self._deployment)
        )
        with self._lock:
            self._last_refresh = now
            self._max_queued = info.get("max_queued_requests", -1)
            if info["version"] != self._version:
                self._version = info["version"]
                self._replicas = info["replicas"]
                live = {rid for rid, _, _ in self._replicas}
                self._inflight = {
                    rid: n for rid, n in self._inflight.items() if rid in live
                }

    def _wait_for_replicas(self, timeout: float = 30.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            self._refresh(block=True)
            if self._replicas:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"no running replicas for deployment "
            f"{self._app}/{self._deployment} after {timeout}s"
        )

    # -- scheduling -----------------------------------------------------------

    def _pick(self):
        """Power-of-two-choices on local in-flight counts; skips replicas at
        max_ongoing_requests when an alternative exists."""
        with self._lock:
            replicas = list(self._replicas)
        if not replicas:
            self._wait_for_replicas()
            with self._lock:
                replicas = list(self._replicas)
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)
        na = self._inflight.get(a[0], 0)
        nb = self._inflight.get(b[0], 0)
        return a if na <= nb else b

    def total_inflight(self) -> int:
        with self._lock:
            return sum(self._inflight.values())

    def dispatch(self, method_name: Optional[str], args, kwargs, streaming: bool):
        """Route one request; returns (replica_id, ObjectRef-or-generator).

        The dispatch wall-clock (refresh + pick + submit — the router's
        own contribution to request latency) lands in the
        serve_router_dispatch_seconds histogram; the trace context, when
        the caller carries one, rides the actor-task envelope the
        `.remote()` below captures, so the replica executes inside the
        request's trace."""
        t0 = time.perf_counter()
        self._refresh()
        if self._max_queued >= 0 and self.total_inflight() >= self._max_queued + len(
            self._replicas
        ):
            raise BackpressureError(
                f"deployment {self._app}/{self._deployment}: "
                f"max_queued_requests={self._max_queued} exceeded"
            )
        rid, handle, _max_ongoing = self._pick()
        with self._lock:
            self._inflight[rid] = self._inflight.get(rid, 0) + 1
        try:
            if streaming:
                ref = handle.handle_request_streaming.options(
                    num_returns="streaming"
                ).remote(method_name, args, kwargs)
            else:
                ref = handle.handle_request.remote(method_name, args, kwargs)
        except Exception:
            with self._lock:
                self._inflight[rid] = max(0, self._inflight.get(rid, 1) - 1)
            raise
        finally:
            from ray_tpu.obs import slo

            slo.record_dispatch(
                self._app, self._deployment, time.perf_counter() - t0
            )
        return rid, ref

    def complete(self, rid: str) -> None:
        with self._lock:
            self._inflight[rid] = max(0, self._inflight.get(rid, 1) - 1)
