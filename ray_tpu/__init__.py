"""ray_tpu: a TPU-native distributed compute framework.

Capabilities of the reference system (comaniac/ray, surveyed in
SURVEY.md): tasks/actors/objects/placement-groups under a cluster
scheduler — rebuilt TPU-first, with JAX device meshes, XLA/ICI
collectives, and Pallas kernels as the compute substrate instead of
CUDA/NCCL.
"""

__version__ = "0.1.0"

_API_EXPORTS = {}


def __getattr__(name):
    # Public core API (init/remote/get/put/wait/actor/...) is re-exported
    # lazily from ray_tpu.core.api to keep `import ray_tpu` light for
    # model-only users (jax imports are heavy already).
    try:
        from ray_tpu.core import api
    except ImportError:
        raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}") from None
    if hasattr(api, name):
        return getattr(api, name)
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")
