"""Multi-step decode: N tokens per host round-trip.

On the single-chip serving path every decode step costs one host sync
(logits down, sampled token back up) — on a tunneled device that round
trip dwarfs the compute (measured ~70-300 ms vs ~5 ms of model math for
a 400M model). The TPU-native fix is to keep the whole
decode-sample-feed loop ON DEVICE: `lax.scan` over `decode_step` with
vectorized sampling between iterations, slots computed from the block
tables in-graph, ONE transfer of [n_steps, B] tokens at the end.

Overshoot semantics: stop conditions (EOS, stop ids, max_tokens) are
evaluated host-side after the chunk; tokens past a stop are discarded
and their KV (which only ever lands in the request's own allocated,
unsealed blocks) is released with the sequence. The reference's vLLM
engine makes the same trade in its multi-step scheduling mode.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp

from ray_tpu.llm.sampling import sample_tokens
from ray_tpu.models.llama_decode import decode_step


_chunk_hist = None
_runtime_hooks = None  # (get_runtime, TaskState), resolved once


def _timeline_hooks():
    """One-time resolution of the timeline-export hooks: the runtime
    import is heavyweight and record_chunk sits on the decode hot path
    (it used to pay these imports EVERY chunk)."""
    global _runtime_hooks
    if _runtime_hooks is None:
        from ray_tpu.core import runtime as rt
        from ray_tpu.core.events import TaskState

        _runtime_hooks = (rt.get_runtime, TaskState)
    return _runtime_hooks


def chunk_histogram():
    """Per-chunk wall-time histogram (engine hook, EngineConfig.profile):
    one observation per decode round trip, tagged by device-side step
    count and sampler mode, on the dashboard /metrics endpoint. Cached —
    re-registering per chunk would take the process-wide registry lock
    on the decode hot path."""
    global _chunk_hist
    if _chunk_hist is None:
        from ray_tpu.util.metrics import Histogram

        _chunk_hist = Histogram(
            "llm_decode_chunk_ms",
            description="profiler: wall ms per decode chunk round trip "
            "(dispatch + device steps + host sync)",
            boundaries=[0.5, 1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 5000],
            tag_keys=("n_steps", "mode"),
        )
    return _chunk_hist


def record_chunk(ms: float, n_steps: int, mode: str, batch_size: int) -> None:
    """Publish one decode-chunk measurement: histogram + timeline span.
    Observability must not break decode: every failure mode (metric name
    registered with another type, runtime init, ...) is swallowed."""
    try:
        chunk_histogram().observe(
            ms, tags={"n_steps": str(n_steps), "mode": mode}
        )
        get_runtime, TaskState = _timeline_hooks()
        buf = get_runtime().task_events
        end = time.time()
        span = f"profile-decode-chunk-{time.monotonic_ns()}"
        name = f"profile:decode_chunk:{n_steps}x{batch_size}"
        buf.record(span, name, TaskState.RUNNING, kind="profile",
                   worker="llm-engine", ts=end - ms / 1e3)
        buf.record(span, name, TaskState.FINISHED, kind="profile",
                   worker="llm-engine", ts=end)
    except Exception:  # noqa: BLE001 — observability must not break decode
        pass


def decode_chunk(
    params,
    tokens: jax.Array,        # [B] current tokens
    positions: jax.Array,     # [B] absolute positions of `tokens`
    block_tables: jax.Array,  # [B, MB]
    context_lens: jax.Array,  # [B] INCLUDING the current token
    cache,
    temperatures: jax.Array,  # [B]
    top_ks: jax.Array,        # [B]
    top_ps: jax.Array,        # [B]
    keys: jax.Array,          # [B] STABLE per-request PRNG keys
    starts: jax.Array,        # [B] absolute output index of step 0's token
    remaining: jax.Array,     # [B] tokens each request can still KEEP
    config,
    *,
    n_steps: int,
    block_size: int,
    trash_slot: int,
    attn_impl: str = "auto",
    sample_mode: str = "full",  # static sampler fast path (llm.sampling)
    lora=None,
):
    """Returns (tokens [n_steps, B], logprobs [n_steps, B], cache).

    Sampling key for step s = fold(request key, starts + s) — a pure
    function of the request and the token's absolute index, so seeded
    requests reproduce regardless of chunk partitioning or batch-mates.
    Steps at/past `remaining` (overshoot the host will discard) write
    the trash page: their KV blocks were never reserved.
    """
    B = tokens.shape[0]
    rows = jnp.arange(B)
    # pad-row mask decided ONCE from the chunk's entry state: inside the
    # scan ctx increments every step, so a `ctx > 0` check would flip a
    # pad row "valid" after the first iteration and its writes (block
    # table row is all zeros) would clobber block 0 — a real sequence's
    # block
    valid = context_lens > 0

    def one_step(carry, s):
        tok, pos, ctx, cache = carry
        # slot for the fed token straight from the block table; padded
        # rows and unreserved overshoot steps write the trash page, NOT
        # block 0
        slot = (
            block_tables[rows, pos // block_size] * block_size
            + pos % block_size
        )
        slot = jnp.where(valid & (s < remaining), slot, trash_slot)
        logits, new_cache = decode_step(
            params, tok, pos, slot, block_tables, ctx, cache, config,
            block_size=block_size, attn_impl=attn_impl, lora=lora,
        )
        step_keys = jax.vmap(jax.random.fold_in)(keys, starts + s)
        next_tok, logprob = sample_tokens(
            logits, temperatures, top_ks, top_ps, step_keys, mode=sample_mode
        )
        return (next_tok, pos + 1, ctx + 1, new_cache), (next_tok, logprob)

    (_, _, _, cache), (toks, logprobs) = jax.lax.scan(
        one_step,
        (tokens, positions, context_lens, cache),
        jnp.arange(n_steps),
    )
    return toks, logprobs, cache
