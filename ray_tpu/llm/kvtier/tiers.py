"""Tiered spill/resurrect machinery behind one engine's prefix cache.

``KVTierManager`` hangs off an ``LLMEngine`` and listens to its
``BlockAllocator``:

 * ``on_seal`` — a full block was registered reusable: remember its
   chain metadata (parent hash, tokens, prefix length) and advertise
   the HBM row to the prefix index.
 * ``on_evict`` — allocation pressure is about to reuse a zero-ref
   cached block. r17 gathered the pages to host SYNCHRONOUSLY here —
   one blocking device→host copy + CRC per evicted block, on the
   allocation hot path. r18 makes the spill ASYNC AND BATCHED: the
   eviction window only captures the block's pages as a device-side
   slice (cheap — the copy happens on device, off the host's critical
   path) and queues it; a spill worker coalesces everything queued into
   ONE batched device→host gather overlapping decode, then seals each
   block as a CRC-sealed ``SpilledBlock`` (the r10 ``KVHandoff`` seal
   machinery, so spill integrity and handoff integrity are ONE code
   path). A probe/get that races the worker sees pending entries as
   host-tier residents; ``get`` materializes on demand, so nothing the
   sync path could serve is ever missed. If the engine dies mid-gather
   the entry is simply dropped — a future cache miss, never a torn
   (half-sealed) resurrection.

Resurrection runs in the engine's prefill admission
(``LLMEngine._resurrect_tiers``): blocks past the HBM match are pulled
back with ``take_verified`` (seal + token check — a corrupt copy is
dropped and counted, never scattered) and re-enter the paged cache via
the same jitted scatter ``import_handoff`` uses.

Cross-engine fetch (r18, ``ray_tpu.llm.kvfetch``): ``serve_fetch`` is
the SOURCE side of the fetch plane — any same-weights replica may pull
this engine's spilled blocks (a ``SpilledBlock`` already IS a sealed
``KVHandoff``, so the wire format existed since r10). Fetch reads are
non-destructive; the REQUESTER re-verifies every block before its
pages touch a cache. The ``llm.kvfetch`` chaos site lives here so
DROP/CORRUPT_KV_TRANSFER cover every fetch backend through one hook.

Thread model (r18): the engine's own serving thread still drives every
allocator callback and admission, but the spill worker, the kvfetch
prefetch worker, and OTHER engines' fetch pulls now read/mutate the
tier tables concurrently — ``_lock`` (an RLock) guards ``_meta`` /
``_host`` / ``_obj`` / ``_pending``. Blocking work (device→host
copies, chaos fires, object-store serialization) happens OUTSIDE the
lock; only dict/LRU motion happens under it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Optional

import numpy as np

from ray_tpu.chaos import harness as _chaos
from ray_tpu.llm.kvtier.config import (
    TIER_CODES,
    TIER_HBM,
    TIER_HOST,
    TIER_OBJECT,
    KVTierConfig,
)
from ray_tpu.utils.ids import ObjectID
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.llm.kvtier")


@dataclasses.dataclass
class SpilledBlock:
    """One sealed full block outside HBM: its pages as a CRC-sealed
    KVHandoff (pages [L, KVH, block_size, D], prompt_token_ids = the
    block's tokens) plus the chain metadata resurrection re-links."""

    handoff: Any          # llm.disagg.handoff.KVHandoff
    parent_hash: int
    n_prefix_tokens: int  # prompt tokens covered through this block

    @property
    def nbytes(self) -> int:
        return int(self.handoff.nbytes)

    @property
    def tokens(self) -> tuple:
        return tuple(self.handoff.prompt_token_ids)


@dataclasses.dataclass
class _PendingSpill:
    """An evicted block captured on-device, awaiting the worker's
    batched gather. ``k_dev``/``v_dev`` are device arrays sliced in the
    eviction window (the pages' value at eviction time — jax sequences
    the slice before any later in-place cache update)."""

    content_hash: int
    parent_hash: int
    tokens: tuple
    n_prefix_tokens: int
    k_dev: Any
    v_dev: Any
    t_enqueued: float


class KVTierManager:
    """HBM -> host DRAM -> object store ladder for one engine."""

    def __init__(self, engine: Any, config: Optional[KVTierConfig] = None):
        self.engine = engine
        self.config = config or KVTierConfig()
        c = self.config
        # guards _meta/_host/_obj/_pending: the spill worker, the
        # kvfetch prefetch worker, and remote fetch pulls all touch the
        # tier tables off the engine thread (see module docstring)
        self._lock = threading.RLock()
        # chain metadata for hashes currently sealed in HBM: the spill
        # path needs (parent, tokens, prefix length) the allocator's
        # hash->block map doesn't carry. Bounded by the HBM block count.
        self._meta: dict[int, tuple] = {}  # h -> (parent, tokens, n_prefix)
        # h -> root salt of its chain (first block's parent IS the salt;
        # propagated hash-to-hash at seal time, same derivation as the
        # allocator's). Chain metadata, not residency: entries survive
        # spill/evict so a scoped invalidation (one adapter swapped)
        # finds every tier's copies; cleared only by invalidate_all.
        self._root: dict[int, int] = {}
        # host DRAM tier: bounded LRU of SpilledBlocks
        self._host: "OrderedDict[int, SpilledBlock]" = OrderedDict()
        self._host_bytes = 0
        # object-store tier: LRU of ids into the (possibly shared) store
        from ray_tpu.core.object_store import ObjectStore

        self._store = c.object_store or ObjectStore()
        self._obj: "OrderedDict[int, tuple]" = OrderedDict()  # h -> (oid, nbytes, parent, n_prefix)
        self._obj_bytes = 0
        # async spill queue: hash -> _PendingSpill, drained by the spill
        # worker in ONE batched gather per wakeup (bounded — a queued
        # entry pins its device slices, so overflow drops the oldest)
        self._pending: "OrderedDict[int, _PendingSpill]" = OrderedDict()
        # hashes the worker has popped but not yet inserted (the gather
        # window): get() waits for them instead of reporting a miss the
        # sync path would have served
        self._gathering: dict[int, bool] = {}
        # invalidation generation: every insert that began BEFORE an
        # invalidate_all (weight swap) must be dropped, or the worker /
        # a prefetch fetch would re-insert KV computed under the OLD
        # weights after the swap wiped every tier — verification cannot
        # catch that (the pages are intact, just stale), only this can
        self.generation = 0
        self._spill_wake = threading.Event()
        self._spill_stop = False
        self._spill_thread: Optional[threading.Thread] = None
        if c.async_spill:
            t = threading.Thread(
                target=self._spill_worker, name="kvtier-spill", daemon=True
            )
            t.start()
            self._spill_thread = t
        # prefix index publishing (telemetry-style epoch banking: the
        # epoch survives this object, the seq only this incarnation)
        self.index: Any = None
        self.engine_key: str = getattr(engine, "model_tag", "engine")
        # where remote engines can PULL this engine's spilled blocks
        # (rides the index snapshot; None = in-process registry only)
        self.fetch_addr: Any = None
        self._epoch = int(time.time() * 1000)
        self._seq = 0
        self._index_dirty = True
        self._index_next = 0.0
        self._index_refresh_next = 0.0
        # stats
        self.spilled_bytes = {TIER_HOST: 0, TIER_OBJECT: 0}
        self.resurrected_tokens = {TIER_HOST: 0, TIER_OBJECT: 0}
        self.corrupt_dropped = {TIER_HOST: 0, TIER_OBJECT: 0}
        self.spills_dropped = 0   # chaos DROP_KV_TRANSFER at the spill site
        self.spill_queue_dropped = 0  # overflowed the bounded pending queue
        self.spill_gather_failures = 0  # worker gather died: block missed
        self.evicted_blocks = 0   # fell off the deepest tier (gone for good)
        self.fetch_blocks_served = 0  # blocks pulled by remote engines
        self.fetch_bytes_served = 0
        # per-eviction wall time INSIDE the allocation path (the r18
        # async-spill headline: capture-only vs r17's blocking gather)
        self.spill_wall_ms: deque = deque(maxlen=1024)
        # jitted page capture (one compiled dynamic-slice program, the
        # block offset traced): eager slicing re-builds the op per call
        # and costs an order of magnitude more on the allocation path
        self._capture_fn = None
        self._bind_allocator()

    # -- allocator listeners ---------------------------------------------------

    def _bind_allocator(self) -> None:
        alloc = self.engine.allocator
        alloc.seal_listener = self.on_seal
        alloc.evict_listener = self.on_evict
        alloc.drop_listener = self.on_drop

    def rebind_allocator(self) -> None:
        """The engine rebuilt its allocator/KV cache (recover(rebuild_kv)):
        HBM rows are gone, but spilled copies were written from pages
        that were correct when sealed — they stay resurrectable (pending
        captures included: their device slices were taken before the
        rebuild and are independent buffers)."""
        with self._lock:
            self._meta.clear()
            self._index_dirty = True
        self._bind_allocator()

    def on_seal(self, block_id: int, content_hash: int, parent_hash: int,
                tokens: tuple, n_prefix_tokens: int) -> None:
        with self._lock:
            self._meta[content_hash] = (parent_hash, tuple(tokens),
                                        int(n_prefix_tokens))
            self._root[content_hash] = self._root.get(parent_hash, parent_hash)
            self._index_dirty = True

    def on_evict(self, block_id: int, content_hash: int) -> None:
        """A zero-ref sealed block is being reused by the allocator:
        spill its pages down the ladder before they are overwritten.
        Never throws into allocation (the allocator call site also
        guards) — a failed spill is just a future cache miss. With
        ``async_spill`` the hot path only slices the pages ON DEVICE
        and enqueues; the worker does the host gather off-path."""
        t0 = time.perf_counter()
        with self._lock:
            meta = self._meta.pop(content_hash, None)
            self._index_dirty = True
            gen = self.generation
        if meta is None:
            return  # sealed before the manager attached, or already spilled
        if self.config.host_bytes <= 0 and self.config.object_bytes <= 0:
            return
        parent, tokens, n_prefix = meta
        try:
            if self.config.async_spill:
                k_dev, v_dev = self._capture_block(block_id)
                entry = _PendingSpill(
                    content_hash=content_hash, parent_hash=parent,
                    tokens=tokens, n_prefix_tokens=n_prefix,
                    k_dev=k_dev, v_dev=v_dev, t_enqueued=time.time(),
                )
                with self._lock:
                    self._pending[content_hash] = entry
                    self._pending.move_to_end(content_hash)
                    while len(self._pending) > self.config.spill_queue_depth:
                        self._pending.popitem(last=False)
                        self.spill_queue_dropped += 1
                self._spill_wake.set()
            else:
                k, v = self._capture_block(block_id)
                sb = self._materialize(content_hash, parent, tokens,
                                       n_prefix, k, v)
                if sb is not None:
                    self._insert(content_hash, sb, gen=gen)
        except Exception:  # noqa: BLE001 — spill must never break allocation
            logger.exception("kvtier spill of block %d failed", block_id)
            return
        finally:
            with self._lock:
                self.spill_wall_ms.append((time.perf_counter() - t0) * 1e3)

    def on_drop(self, salt: Optional[int] = None) -> None:
        """The allocator invalidated its prefix cache (weight swap /
        LoRA slot reuse): cached K/V no longer matches what the current
        weights would compute, in EVERY tier. Cascade — scoped to one
        chain root's salt when the allocator scoped its drop (a single
        adapter swapped under a fleet canary), everything otherwise."""
        if salt is None:
            self.invalidate_all()
        else:
            self.invalidate_salt(salt)

    # back-compat alias (pre-r21 binding name)
    def on_drop_all(self) -> None:
        self.invalidate_all()

    # -- spill path ------------------------------------------------------------

    def _capture_block(self, block_id: int):
        """Slice one block's pages as DEVICE arrays (the eviction
        window: the victim's pages are intact until its new owner
        writes, and jax sequences this slice before any later in-place
        cache update — the slice result is an independent buffer).
        ONE jitted dynamic-slice program serves every eviction (the
        offset is a traced scalar), so the allocation-path cost is a
        single cached dispatch, not per-call op construction."""
        bs = self.engine.config.block_size
        if self._capture_fn is None:
            import jax

            self._capture_fn = jax.jit(lambda k, v, lo: (
                jax.lax.dynamic_slice_in_dim(k, lo, bs, axis=2),
                jax.lax.dynamic_slice_in_dim(v, lo, bs, axis=2),
            ))
        return self._capture_fn(
            self.engine.cache["k"], self.engine.cache["v"], block_id * bs
        )

    def _materialize(self, content_hash: int, parent: int, tokens: tuple,
                     n_prefix: int, k, v) -> Optional[SpilledBlock]:
        """Host-side half of a spill: device→host copy, CRC seal, chaos
        gate. Runs on the spill worker (async) or inline (sync path /
        a ``get`` racing the worker). Never called under ``_lock``."""
        from ray_tpu.llm.disagg.handoff import KVHandoff

        c = self.engine.config
        h = KVHandoff(
            request_id=f"kvtier-{content_hash & 0xFFFFFFFF:08x}",
            prompt_token_ids=list(tokens),
            output_token_ids=[],
            sampling_params=None,
            key_data=np.zeros(1, np.uint32),
            num_kv_tokens=c.block_size,
            k_pages=np.asarray(k),
            v_pages=np.asarray(v),
            model_sig=(c.model.n_layers, c.model.n_kv_heads,
                       c.model.head_dim),
        ).seal()
        if _chaos.ACTIVE is not None:
            for _f in _chaos.fire(
                "llm.kvtier.spill",
                kinds=(_chaos.DROP_KV_TRANSFER, _chaos.CORRUPT_KV_TRANSFER),
                chain=content_hash,
            ):
                if _f.kind == _chaos.DROP_KV_TRANSFER:
                    # the spill is silently lost: a later probe misses
                    # and recomputes — the failure mode of a torn host
                    self.spills_dropped += 1
                    return None
                if _f.kind == _chaos.CORRUPT_KV_TRANSFER:
                    # bit-flip AFTER sealing (copy-on-corrupt: the
                    # gathered view may be read-only): resurrection's
                    # verify() must catch it (never wrong tokens)
                    kc = np.array(h.k_pages, copy=True)
                    flat = kc.view(np.uint8).reshape(-1)
                    if flat.size:
                        mid = flat.size // 2
                        span = max(1, min(16, flat.size - mid))
                        flat[mid:mid + span] ^= 0xFF
                    h.k_pages = kc
        return SpilledBlock(handoff=h, parent_hash=parent,
                            n_prefix_tokens=n_prefix)

    def _insert(self, content_hash: int, sb: SpilledBlock,
                gen: Optional[int] = None) -> None:
        """Insert into the first enabled deep tier. ``gen`` is the
        generation the caller observed when it BEGAN producing ``sb``
        (spill capture / remote fetch): if an invalidate_all landed in
        between, the block was computed under dead weights and must be
        dropped — held under the (reentrant) lock so the check and the
        insert are one atomic step."""
        with self._lock:
            if gen is not None and gen != self.generation:
                return
            if self.config.host_bytes > 0:
                self._host_insert(content_hash, sb)
            else:
                self._object_insert(content_hash, sb)

    def _spill_worker(self) -> None:
        """Drain the pending queue in BATCHES: every wakeup converts all
        queued device slices in one coalesced stacked gather (one
        device→host transfer instead of one per block), then seals and
        inserts each block. A gather that dies drops exactly the blocks
        it carried — counted misses, never a torn entry."""
        while not self._spill_stop:
            # bounded park: a stop() between wakeups is honored within
            # one poll slice
            self._spill_wake.wait(timeout=0.1)
            self._spill_wake.clear()
            self._drain_pending()

    def _drain_pending(self, only_hash: Optional[int] = None) -> None:
        with self._lock:
            gen = self.generation
            if only_hash is not None:
                e = self._pending.pop(only_hash, None)
                entries = [e] if e is not None else []
            else:
                entries = list(self._pending.values())
                self._pending.clear()
            for e in entries:
                # the gather window: get() waits for these instead of
                # reporting a miss the sync path would have served
                self._gathering[e.content_hash] = True
        if not entries:
            return
        try:
            try:
                # the coalesced gather: ONE device_get over every queued
                # slice (a pytree copy, no compilation — a jnp.stack here
                # would recompile per batch size and contend with the
                # engine thread's dispatches)
                import jax

                rows = jax.device_get([(e.k_dev, e.v_dev) for e in entries])
            except Exception:  # noqa: BLE001 — died mid-gather: blocks missed
                self.spill_gather_failures += len(entries)
                logger.exception(
                    "kvtier spill gather of %d block(s) failed; "
                    "entries dropped", len(entries),
                )
                return
            for e, (k, v) in zip(entries, rows):
                try:
                    sb = self._materialize(e.content_hash, e.parent_hash,
                                           e.tokens, e.n_prefix_tokens, k, v)
                except Exception:  # noqa: BLE001
                    self.spill_gather_failures += 1
                    continue
                if sb is not None:
                    self._insert(e.content_hash, sb, gen=gen)
        finally:
            with self._lock:
                for e in entries:
                    self._gathering.pop(e.content_hash, None)

    def flush_spills(self, timeout_s: float = 10.0) -> bool:
        """Block (bounded) until every pending spill has materialized —
        tests and benches use it to observe the post-spill state the
        sync path produced immediately."""
        deadline = time.monotonic() + timeout_s
        self._spill_wake.set()
        while time.monotonic() < deadline:
            with self._lock:
                if not self._pending:
                    return True
            if self._spill_thread is None:
                self._drain_pending()
            else:
                self._spill_wake.set()
                time.sleep(0.002)
        return False

    def stop(self) -> None:
        """Tear down the spill worker (engine shutdown in tests)."""
        self._spill_stop = True
        self._spill_wake.set()
        if self._spill_thread is not None:
            self._spill_thread.join(timeout=2)

    def _host_insert(self, content_hash: int, sb: SpilledBlock) -> None:
        with self._lock:
            old = self._host.get(content_hash)
            if old is not None:
                # re-spill of a hash still resident (resurrection aborted on
                # allocation pressure, then the recompute re-sealed and
                # re-evicted it): replace, don't double-count the bytes
                self._host_bytes -= old.nbytes
            self._host[content_hash] = sb
            self._host.move_to_end(content_hash)
            self._host_bytes += sb.nbytes
            self.spilled_bytes[TIER_HOST] += sb.nbytes
            demote: list = []
            while self._host_bytes > self.config.host_bytes and self._host:
                old_h, old = self._host.popitem(last=False)
                self._host_bytes -= old.nbytes
                if self.config.object_bytes > 0:
                    demote.append((old_h, old))
                else:
                    self.evicted_blocks += 1
            self._index_dirty = True
        self._count_spill(TIER_HOST, sb.nbytes)
        for old_h, old in demote:
            self._object_insert(old_h, old)

    def _object_insert(self, content_hash: int, sb: SpilledBlock) -> None:
        from ray_tpu.core.object_store import serialize

        # serialization (the expensive host copy) stays outside the lock
        oid = self._object_id(content_hash)
        payload, buffers = serialize(sb)
        with self._lock:
            old = self._obj.pop(content_hash, None)
            if old is not None:
                # replace-in-place: release the old store ref and its bytes
                # before re-putting under the same (hash-derived) object id
                self._obj_bytes -= old[1]
                self._store.remove_ref(old[0])
            self._store.put_serialized(oid, payload, buffers)
            self._obj[content_hash] = (oid, sb.nbytes, sb.parent_hash,
                                       sb.n_prefix_tokens)
            self._obj.move_to_end(content_hash)
            self._obj_bytes += sb.nbytes
            self.spilled_bytes[TIER_OBJECT] += sb.nbytes
            while self._obj_bytes > self.config.object_bytes and self._obj:
                old_h, (old_oid, old_n, _p, _np_) = self._obj.popitem(last=False)
                self._obj_bytes -= old_n
                self._store.remove_ref(old_oid)
                self.evicted_blocks += 1
            self._index_dirty = True
        self._count_spill(TIER_OBJECT, sb.nbytes)

    def _object_id(self, content_hash: int) -> ObjectID:
        digest = hashlib.blake2b(
            f"kvtier:{self.engine_key}:{content_hash}".encode(),
            digest_size=16,
        ).digest()
        return ObjectID(digest)

    def _count_spill(self, tier: str, nbytes: int) -> None:
        try:
            from ray_tpu.llm.kvtier import metrics as kvtier_metrics

            kvtier_metrics.spilled_bytes_counter().inc(
                nbytes, tags={"model": self.engine.model_tag, "tier": tier}
            )
        except Exception:  # noqa: BLE001 — observability never breaks serving
            pass

    # -- resurrect path --------------------------------------------------------

    def peek(self, content_hash: int) -> Optional[str]:
        """Which deep tier holds this hash (read-only; no LRU motion).
        A spill still pending its gather counts as host-resident — it
        WILL land there, and ``get`` can materialize it on demand. That
        includes the MID-GATHER window: the worker pops a batch out of
        ``_pending`` into ``_gathering`` before the device→host copy,
        and a probe landing inside that window must not read the block
        as evicted-everywhere (``get`` already waits on ``_gathering``;
        the probe has to agree with what ``get`` would serve)."""
        with self._lock:
            if (content_hash in self._host or content_hash in self._pending
                    or content_hash in self._gathering):
                return TIER_HOST
            if content_hash in self._obj:
                return TIER_OBJECT
        return None

    def get(self, content_hash: int) -> Optional[tuple]:
        """(tier, SpilledBlock) without removing the entry — the caller
        commits with ``promoted`` only after the scatter landed. A
        pending (un-gathered) spill is materialized inline so the async
        queue never turns a sync-path hit into a miss."""
        with self._lock:
            pending = content_hash in self._pending
        if pending:
            self._drain_pending(only_hash=content_hash)
        # mid-gather window: the worker popped this hash but hasn't
        # inserted it yet — wait (bounded; roughly what the sync path
        # would have paid for the gather) instead of reporting a miss
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with self._lock:
                busy = content_hash in self._gathering
            if not busy:
                break
            time.sleep(0.001)
        with self._lock:
            sb = self._host.get(content_hash)
            if sb is not None:
                self._host.move_to_end(content_hash)
                return TIER_HOST, sb
            rec = self._obj.get(content_hash)
            oid = rec[0] if rec is not None else None
        if oid is None:
            return None
        from ray_tpu.core.object_store import deserialize

        try:
            payload, buffers = self._store.serialized_get(oid, timeout=1.0)
            sb = deserialize(payload, buffers)
        except Exception:  # noqa: BLE001 — torn store entry = miss
            self._drop_entry(content_hash, TIER_OBJECT)
            return None
        with self._lock:
            if content_hash in self._obj:
                self._obj.move_to_end(content_hash)
        return TIER_OBJECT, sb

    def take_verified(self, content_hash: int,
                      expect_tokens: tuple) -> Optional[tuple]:
        """(tier, SpilledBlock) iff the seal verifies AND the stored
        tokens match the prompt's block — a corrupt or hash-colliding
        entry is dropped and counted, and the caller recomputes from
        this block on (never wrong tokens)."""
        got = self.get(content_hash)
        if got is None:
            return None
        tier, sb = got
        if not self.verify_block(sb, expect_tokens):
            self.corrupt_dropped[tier] += 1
            self._drop_entry(content_hash, tier)
            try:
                from ray_tpu.llm.kvtier import metrics as kvtier_metrics

                kvtier_metrics.corrupt_dropped_counter().inc(
                    1, tags={"model": self.engine.model_tag, "tier": tier}
                )
            except Exception:  # noqa: BLE001
                pass
            logger.warning(
                "kvtier: dropped corrupt %s-tier block (chain %x); "
                "falling back to recompute", tier, content_hash & 0xFFFFFFFF,
            )
            return None
        return tier, sb

    @staticmethod
    def verify_block(sb: SpilledBlock, expect_tokens: tuple) -> bool:
        """Seal + token check shared by local resurrection and the
        kvfetch requester (a fetched block goes through the SAME gate
        before its pages touch any cache)."""
        try:
            return (tuple(sb.tokens) == tuple(expect_tokens)
                    and sb.handoff.verify())
        except Exception:  # noqa: BLE001 — malformed entry = corrupt
            return False

    def promoted(self, content_hash: int, tier: str) -> None:
        """The block is back in HBM (resurrected + re-registered): drop
        the deep-tier copy; the seal listener re-advertises it as hbm."""
        self._drop_entry(content_hash, tier)

    def count_resurrected(self, tier: str, n_tokens: int) -> None:
        self.resurrected_tokens[tier] = (
            self.resurrected_tokens.get(tier, 0) + n_tokens
        )
        try:
            from ray_tpu.llm.kvtier import metrics as kvtier_metrics

            kvtier_metrics.resurrected_tokens_counter().inc(
                n_tokens, tags={"model": self.engine.model_tag, "tier": tier}
            )
        except Exception:  # noqa: BLE001
            pass

    def _drop_entry(self, content_hash: int, tier: str) -> None:
        with self._lock:
            self._pending.pop(content_hash, None)
            if tier == TIER_HOST:
                sb = self._host.pop(content_hash, None)
                if sb is not None:
                    self._host_bytes -= sb.nbytes
            else:
                rec = self._obj.pop(content_hash, None)
                if rec is not None:
                    self._obj_bytes -= rec[1]
                    self._store.remove_ref(rec[0])
            self._index_dirty = True

    # -- cross-engine fetch (the llm.kvfetch source side) ----------------------

    def serve_fetch(self, hashes: list, tokens_list: list) -> list:
        """Serve spilled blocks to a REMOTE same-weights engine (the
        source half of ``ray_tpu.llm.kvfetch``). Non-destructive: the
        local copy stays resident (it may be promoted here later). The
        requester re-verifies every block before scattering, so a
        corrupt entry shipped from here is ITS counted drop.

        This is the ``llm.kvfetch`` chaos site: DROP_KV_TRANSFER fails
        the whole pull with a typed error (the requester degrades to
        local-tiers + recompute), CORRUPT_KV_TRANSFER bit-flips the
        first served block's pages after its seal (caught by the
        requester's verify — never wrong tokens)."""
        from ray_tpu.llm.kvfetch.plane import KVFetchError

        corrupt = False
        if _chaos.ACTIVE is not None:
            for _f in _chaos.fire(
                "llm.kvfetch",
                kinds=(_chaos.DROP_KV_TRANSFER, _chaos.CORRUPT_KV_TRANSFER,
                       _chaos.DELAY_RPC),
                engine=self.engine_key, n_blocks=len(hashes),
            ):
                if _f.kind == _chaos.DROP_KV_TRANSFER:
                    raise KVFetchError(
                        f"chaos: dropped kv fetch from {self.engine_key!r}"
                    )
                if _f.kind == _chaos.DELAY_RPC:
                    time.sleep(_f.delay_s)
                if _f.kind == _chaos.CORRUPT_KV_TRANSFER:
                    corrupt = True
        out: list = []
        for h, toks in zip(hashes, tokens_list):
            got = self.get(int(h))
            if got is None:
                out.append(None)
                continue
            _tier, sb = got
            if tuple(sb.tokens) != tuple(toks):
                out.append(None)  # hash collision: not the caller's block
                continue
            if corrupt:
                # copy-on-corrupt AFTER the seal (the resident entry
                # stays intact): the requester's verify must catch it
                kc = np.array(sb.handoff.k_pages, copy=True)
                flat = kc.view(np.uint8).reshape(-1)
                if flat.size:
                    mid = flat.size // 2
                    span = max(1, min(16, flat.size - mid))
                    flat[mid:mid + span] ^= 0xFF
                sb = SpilledBlock(
                    handoff=dataclasses.replace(sb.handoff, k_pages=kc),
                    parent_hash=sb.parent_hash,
                    n_prefix_tokens=sb.n_prefix_tokens,
                )
                corrupt = False  # one block is enough to prove the gate
            out.append(sb)
            self.fetch_blocks_served += 1
            self.fetch_bytes_served += sb.nbytes
        return out

    def adopt_fetched(self, content_hash: int, sb: SpilledBlock,
                      gen: Optional[int] = None) -> None:
        """A verified block PULLED from a remote engine joins the local
        host tier (cross-engine resurrection, ray_tpu.llm.kvfetch): it
        is now resurrectable here even if the tick scatter never runs,
        and the next index snapshot advertises this engine as a holder
        too. Rides the ordinary bounded-LRU insert — fetched bytes are
        cache, never unbounded growth. ``gen`` = the generation when
        the fetch began; a weight swap in between drops the adoption."""
        self._insert(content_hash, sb, gen=gen)

    # -- probes (read-only; the routing signal) --------------------------------

    def probe_tiers(self, tokens: list, salt: int = 0) -> dict:
        """Longest contiguous resurrectable prefix of ``tokens`` across
        ALL tiers, tier-discounted. Read-only: no refs, no LRU motion.
        Returns {"n_tokens", "discounted", "by_tier": {tier: tokens}}."""
        from ray_tpu.llm.kv_cache import BlockAllocator

        alloc = self.engine.allocator
        bs = alloc.block_size
        c = self.config
        h = salt
        n = 0
        discounted = 0.0
        by_tier: dict[str, int] = {}
        for i in range(len(tokens) // bs):
            blk = tuple(tokens[i * bs : (i + 1) * bs])
            h = BlockAllocator.chain_hash(h, blk)
            if alloc.contains_hash(h):
                tier = TIER_HBM
            else:
                tier = self.peek(h)
                if tier is None:
                    break
            n += bs
            discounted += c.weight(tier) * bs
            by_tier[tier] = by_tier.get(tier, 0) + bs
        return {"n_tokens": n, "discounted": discounted, "by_tier": by_tier}

    # -- invalidation ----------------------------------------------------------

    def invalidate_all(self) -> None:
        """Weight swap / adapter churn: every tier's cached K/V is stale.
        Drops host + object entries (pending spills included), forgets
        HBM metadata, and ships an EMPTY index snapshot so the cluster
        stops routing here for prefixes this engine no longer holds."""
        with self._lock:
            # generation bump: an in-flight spill gather or remote fetch
            # that BEGAN before this point must not land afterwards (its
            # pages are intact but computed under the dead weights)
            self.generation += 1
            self._meta.clear()
            self._host.clear()
            self._host_bytes = 0
            self._pending.clear()
            for oid, _n, _p, _np_ in self._obj.values():
                try:
                    self._store.remove_ref(oid)
                except Exception:  # noqa: BLE001
                    pass
            self._obj.clear()
            self._obj_bytes = 0
            self._root.clear()
            self._index_dirty = True
        kvf = getattr(self.engine, "kvfetch", None)
        if kvf is not None:
            # staged prefetch chains and reservations reference pre-swap
            # KV: drop them (and free the reservation refs) NOW, before
            # the engine-thread tick could scatter stale pages
            kvf.reset()
        self.flush_index(force=True)

    def invalidate_salt(self, salt: int) -> None:
        """One adapter swapped (fleet canary / LoRA slot reuse): only
        chains rooted at ``salt`` are stale. Drops those chains' host +
        object + pending entries; every other tenant's tiers survive.
        The generation still bumps — an in-flight gather or fetch has no
        salt attached, so in-flight inserts are (conservatively) dropped
        regardless of chain — and staged prefetches reset for the same
        reason. Resident entries of other salts are what the scoping
        saves, and they are the expensive part."""
        with self._lock:
            self.generation += 1
            doomed = [h for h, r in self._root.items() if r == salt]
            for h in doomed:
                self._root.pop(h, None)
                self._meta.pop(h, None)
                self._pending.pop(h, None)
                sb = self._host.pop(h, None)
                if sb is not None:
                    self._host_bytes -= sb.nbytes
                rec = self._obj.pop(h, None)
                if rec is not None:
                    oid, nbytes, _p, _np_ = rec
                    self._obj_bytes -= nbytes
                    try:
                        self._store.remove_ref(oid)
                    except Exception:  # noqa: BLE001
                        pass
            self._index_dirty = True
        kvf = getattr(self.engine, "kvfetch", None)
        if kvf is not None:
            kvf.reset()
        self.flush_index(force=True)

    # -- prefix-index publishing ----------------------------------------------

    def attach_index(self, index: Any, engine_key: Optional[str] = None,
                     fetch_addr: Any = None) -> None:
        self.index = index
        if engine_key is not None:
            self.engine_key = engine_key
        if fetch_addr is not None:
            self.fetch_addr = fetch_addr
        with self._lock:
            self._index_dirty = True
        self.flush_index(force=True)

    # silent publishers' rows are omitted from lookups at the store's
    # stale_after_s and reaped past its expire horizon, so an engine in
    # steady state (nothing sealing or evicting) must still re-publish
    # on this heartbeat — it also repopulates a restarted GCS
    INDEX_REFRESH_S = 10.0

    def flush_index(self, force: bool = False) -> None:
        """Ship a full snapshot of resident chain hashes (throttled;
        called from the engine's telemetry refresh). Full snapshots +
        (epoch, seq) guarding give telemetry-style staleness semantics:
        a delayed re-send can never resurrect rows a newer snapshot
        dropped. A failed publish re-arms the dirty flag so the next
        throttle tick retries instead of going silent."""
        if self.index is None:
            return
        now = time.monotonic()
        with self._lock:
            due = self._index_dirty or now >= self._index_refresh_next
            if not force and (not due or now < self._index_next):
                return
            self._index_next = now + self.config.index_flush_interval_s
            self._index_refresh_next = now + self.INDEX_REFRESH_S
            rows = []
            for h, (_p, _tokens, n_prefix) in self._meta.items():
                rows.append([h, TIER_CODES[TIER_HBM], n_prefix])
            for h, sb in self._host.items():
                rows.append([h, TIER_CODES[TIER_HOST], sb.n_prefix_tokens])
            for h, e in self._pending.items():
                # queued spills WILL land in the host tier; advertising
                # them now keeps the index one gather ahead of routing
                rows.append([h, TIER_CODES[TIER_HOST], e.n_prefix_tokens])
            for h, (_oid, _n, _parent, n_prefix) in self._obj.items():
                rows.append([h, TIER_CODES[TIER_OBJECT], n_prefix])
            self._seq += 1
            self._index_dirty = False
            seq = self._seq
        ok = False
        try:
            payload = {
                "engine": self.engine_key,
                "epoch": self._epoch,
                "seq": seq,
                "rows": rows,
            }
            if self.fetch_addr is not None:
                payload["fetch_addr"] = list(self.fetch_addr)
            got = self.index.update(payload)
            # GcsPrefixIndex returns a bool; the store returns {"ok": ...}.
            # A "stale" verdict is NOT a failure to retry — it means a
            # newer snapshot (ours: seq only moves forward) already landed.
            ok = bool(got) if not isinstance(got, dict) else bool(got.get("ok"))
            if isinstance(got, dict) and got.get("reason") == "stale":
                ok = True
        except Exception:  # noqa: BLE001 — a dark index costs freshness only
            ok = False
        if not ok:
            with self._lock:
                self._index_dirty = True

    # -- observability ---------------------------------------------------------

    def update_gauges(self) -> None:
        try:
            from ray_tpu.llm.kvtier import metrics as kvtier_metrics

            g = kvtier_metrics.resident_bytes_gauge()
            tag = {"model": self.engine.model_tag}
            with self._lock:
                host_b, obj_b = self._host_bytes, self._obj_bytes
                pending = len(self._pending)
            g.set(host_b, tags={**tag, "tier": TIER_HOST})
            g.set(obj_b, tags={**tag, "tier": TIER_OBJECT})
            from ray_tpu.llm.kvfetch import metrics as kvfetch_metrics

            kvfetch_metrics.spill_queue_gauge().set(pending, tags=tag)
        except Exception:  # noqa: BLE001
            pass

    def stats(self) -> dict:
        with self._lock:
            host_entries, host_b = len(self._host), self._host_bytes
            obj_entries, obj_b = len(self._obj), self._obj_bytes
            pending = len(self._pending)
            walls = sorted(self.spill_wall_ms)
            evicted = self.evicted_blocks
        wall_p99 = walls[min(len(walls) - 1, int(len(walls) * 0.99))] if walls else 0.0
        return {
            "host": {
                "entries": host_entries,
                "resident_bytes": host_b,
                "capacity_bytes": self.config.host_bytes,
            },
            "object": {
                "entries": obj_entries,
                "resident_bytes": obj_b,
                "capacity_bytes": self.config.object_bytes,
            },
            "spilled_bytes_total": dict(self.spilled_bytes),
            "resurrected_tokens": dict(self.resurrected_tokens),
            "corrupt_dropped": dict(self.corrupt_dropped),
            "spills_dropped": self.spills_dropped,
            "evicted_blocks": evicted,
            "spill_queue": {
                "pending": pending,
                "depth_cap": self.config.spill_queue_depth,
                "dropped": self.spill_queue_dropped,
                "gather_failures": self.spill_gather_failures,
                "async": bool(self.config.async_spill),
                "wall_p99_ms": round(wall_p99, 4),
            },
            "fetch_served": {
                "blocks": self.fetch_blocks_served,
                "bytes": self.fetch_bytes_served,
            },
            "index_attached": self.index is not None,
            "engine_key": self.engine_key,
        }
