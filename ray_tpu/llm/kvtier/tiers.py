"""Tiered spill/resurrect machinery behind one engine's prefix cache.

``KVTierManager`` hangs off an ``LLMEngine`` and listens to its
``BlockAllocator``:

 * ``on_seal`` — a full block was registered reusable: remember its
   chain metadata (parent hash, tokens, prefix length) and advertise
   the HBM row to the prefix index.
 * ``on_evict`` — allocation pressure is about to reuse a zero-ref
   cached block: gather its pages off the device (one contiguous slice
   per block — slots are block-major, so this is basic slicing, not a
   gather) and push them down the ladder as a CRC-sealed
   ``SpilledBlock`` (the r10 ``KVHandoff`` seal machinery, so spill
   integrity and handoff integrity are ONE code path).

Resurrection runs in the engine's prefill admission
(``LLMEngine._resurrect_tiers``): blocks past the HBM match are pulled
back with ``take_verified`` (seal + token check — a corrupt copy is
dropped and counted, never scattered) and re-enter the paged cache via
the same jitted scatter ``import_handoff`` uses.

Thread model: every mutating entry point runs on the engine's own
serving thread (allocator calls, prefill admission, telemetry
refresh) — the engine is single-threaded by contract (orchestrator
pools take ``pe.lock`` around every engine call), so the manager
needs no lock of its own; the shared index objects are thread-safe.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from collections import OrderedDict
from typing import Any, Optional

import numpy as np

from ray_tpu.chaos import harness as _chaos
from ray_tpu.llm.kvtier.config import (
    TIER_CODES,
    TIER_HBM,
    TIER_HOST,
    TIER_OBJECT,
    KVTierConfig,
)
from ray_tpu.utils.ids import ObjectID
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.llm.kvtier")


@dataclasses.dataclass
class SpilledBlock:
    """One sealed full block outside HBM: its pages as a CRC-sealed
    KVHandoff (pages [L, KVH, block_size, D], prompt_token_ids = the
    block's tokens) plus the chain metadata resurrection re-links."""

    handoff: Any          # llm.disagg.handoff.KVHandoff
    parent_hash: int
    n_prefix_tokens: int  # prompt tokens covered through this block

    @property
    def nbytes(self) -> int:
        return int(self.handoff.nbytes)

    @property
    def tokens(self) -> tuple:
        return tuple(self.handoff.prompt_token_ids)


class KVTierManager:
    """HBM -> host DRAM -> object store ladder for one engine."""

    def __init__(self, engine: Any, config: Optional[KVTierConfig] = None):
        self.engine = engine
        self.config = config or KVTierConfig()
        c = self.config
        # chain metadata for hashes currently sealed in HBM: the spill
        # path needs (parent, tokens, prefix length) the allocator's
        # hash->block map doesn't carry. Bounded by the HBM block count.
        self._meta: dict[int, tuple] = {}  # h -> (parent, tokens, n_prefix)
        # host DRAM tier: bounded LRU of SpilledBlocks
        self._host: "OrderedDict[int, SpilledBlock]" = OrderedDict()
        self._host_bytes = 0
        # object-store tier: LRU of ids into the (possibly shared) store
        from ray_tpu.core.object_store import ObjectStore

        self._store = c.object_store or ObjectStore()
        self._obj: "OrderedDict[int, tuple]" = OrderedDict()  # h -> (oid, nbytes, parent, n_prefix)
        self._obj_bytes = 0
        # prefix index publishing (telemetry-style epoch banking: the
        # epoch survives this object, the seq only this incarnation)
        self.index: Any = None
        self.engine_key: str = getattr(engine, "model_tag", "engine")
        self._epoch = int(time.time() * 1000)
        self._seq = 0
        self._index_dirty = True
        self._index_next = 0.0
        self._index_refresh_next = 0.0
        # stats
        self.spilled_bytes = {TIER_HOST: 0, TIER_OBJECT: 0}
        self.resurrected_tokens = {TIER_HOST: 0, TIER_OBJECT: 0}
        self.corrupt_dropped = {TIER_HOST: 0, TIER_OBJECT: 0}
        self.spills_dropped = 0   # chaos DROP_KV_TRANSFER at the spill site
        self.evicted_blocks = 0   # fell off the deepest tier (gone for good)
        self._bind_allocator()

    # -- allocator listeners ---------------------------------------------------

    def _bind_allocator(self) -> None:
        alloc = self.engine.allocator
        alloc.seal_listener = self.on_seal
        alloc.evict_listener = self.on_evict
        alloc.drop_listener = self.on_drop_all

    def rebind_allocator(self) -> None:
        """The engine rebuilt its allocator/KV cache (recover(rebuild_kv)):
        HBM rows are gone, but spilled copies were written from pages
        that were correct when sealed — they stay resurrectable."""
        self._meta.clear()
        self._bind_allocator()
        self._index_dirty = True

    def on_seal(self, block_id: int, content_hash: int, parent_hash: int,
                tokens: tuple, n_prefix_tokens: int) -> None:
        self._meta[content_hash] = (parent_hash, tuple(tokens),
                                    int(n_prefix_tokens))
        self._index_dirty = True

    def on_evict(self, block_id: int, content_hash: int) -> None:
        """A zero-ref sealed block is being reused by the allocator:
        spill its pages down the ladder before they are overwritten.
        Never throws into allocation (the allocator call site also
        guards) — a failed spill is just a future cache miss."""
        meta = self._meta.pop(content_hash, None)
        self._index_dirty = True
        if meta is None:
            return  # sealed before the manager attached, or already spilled
        if self.config.host_bytes <= 0 and self.config.object_bytes <= 0:
            return
        parent, tokens, n_prefix = meta
        try:
            sb = self._spill_block(block_id, content_hash, parent, tokens,
                                   n_prefix)
        except Exception:  # noqa: BLE001 — spill must never break allocation
            logger.exception("kvtier spill of block %d failed", block_id)
            return
        if sb is None:
            return
        if self.config.host_bytes > 0:
            self._host_insert(content_hash, sb)
        else:
            self._object_insert(content_hash, sb)

    def on_drop_all(self) -> None:
        """The allocator invalidated its whole prefix cache (weight
        swap / LoRA slot reuse): cached K/V no longer matches what the
        current weights would compute, in EVERY tier. Cascade."""
        self.invalidate_all()

    # -- spill path ------------------------------------------------------------

    def _spill_block(self, block_id: int, content_hash: int, parent: int,
                     tokens: tuple, n_prefix: int) -> Optional[SpilledBlock]:
        from ray_tpu.llm.disagg.handoff import KVHandoff

        c = self.engine.config
        bs = c.block_size
        lo, hi = block_id * bs, (block_id + 1) * bs
        # contiguous slot range: one basic slice per page array, then a
        # host copy — the only device->host traffic the tier ladder does
        k = np.asarray(self.engine.cache["k"][:, :, lo:hi, :])
        v = np.asarray(self.engine.cache["v"][:, :, lo:hi, :])
        h = KVHandoff(
            request_id=f"kvtier-{content_hash & 0xFFFFFFFF:08x}",
            prompt_token_ids=list(tokens),
            output_token_ids=[],
            sampling_params=None,
            key_data=np.zeros(1, np.uint32),
            num_kv_tokens=bs,
            k_pages=k,
            v_pages=v,
            model_sig=(c.model.n_layers, c.model.n_kv_heads,
                       c.model.head_dim),
        ).seal()
        if _chaos.ACTIVE is not None:
            for _f in _chaos.fire(
                "llm.kvtier.spill",
                kinds=(_chaos.DROP_KV_TRANSFER, _chaos.CORRUPT_KV_TRANSFER),
                chain=content_hash,
            ):
                if _f.kind == _chaos.DROP_KV_TRANSFER:
                    # the spill is silently lost: a later probe misses
                    # and recomputes — the failure mode of a torn host
                    self.spills_dropped += 1
                    return None
                if _f.kind == _chaos.CORRUPT_KV_TRANSFER:
                    # bit-flip AFTER sealing (copy-on-corrupt: the
                    # gathered view may be read-only): resurrection's
                    # verify() must catch it (never wrong tokens)
                    kc = np.array(h.k_pages, copy=True)
                    flat = kc.view(np.uint8).reshape(-1)
                    if flat.size:
                        mid = flat.size // 2
                        span = max(1, min(16, flat.size - mid))
                        flat[mid:mid + span] ^= 0xFF
                    h.k_pages = kc
        return SpilledBlock(handoff=h, parent_hash=parent,
                            n_prefix_tokens=n_prefix)

    def _host_insert(self, content_hash: int, sb: SpilledBlock) -> None:
        old = self._host.get(content_hash)
        if old is not None:
            # re-spill of a hash still resident (resurrection aborted on
            # allocation pressure, then the recompute re-sealed and
            # re-evicted it): replace, don't double-count the bytes
            self._host_bytes -= old.nbytes
        self._host[content_hash] = sb
        self._host.move_to_end(content_hash)
        self._host_bytes += sb.nbytes
        self.spilled_bytes[TIER_HOST] += sb.nbytes
        self._count_spill(TIER_HOST, sb.nbytes)
        while self._host_bytes > self.config.host_bytes and self._host:
            old_h, old = self._host.popitem(last=False)
            self._host_bytes -= old.nbytes
            if self.config.object_bytes > 0:
                self._object_insert(old_h, old)
            else:
                self.evicted_blocks += 1
        self._index_dirty = True

    def _object_insert(self, content_hash: int, sb: SpilledBlock) -> None:
        from ray_tpu.core.object_store import serialize

        old = self._obj.pop(content_hash, None)
        if old is not None:
            # replace-in-place: release the old store ref and its bytes
            # before re-putting under the same (hash-derived) object id
            self._obj_bytes -= old[1]
            self._store.remove_ref(old[0])
        oid = self._object_id(content_hash)
        payload, buffers = serialize(sb)
        self._store.put_serialized(oid, payload, buffers)
        self._obj[content_hash] = (oid, sb.nbytes, sb.parent_hash,
                                   sb.n_prefix_tokens)
        self._obj.move_to_end(content_hash)
        self._obj_bytes += sb.nbytes
        self.spilled_bytes[TIER_OBJECT] += sb.nbytes
        self._count_spill(TIER_OBJECT, sb.nbytes)
        while self._obj_bytes > self.config.object_bytes and self._obj:
            old_h, (old_oid, old_n, _p, _np_) = self._obj.popitem(last=False)
            self._obj_bytes -= old_n
            self._store.remove_ref(old_oid)
            self.evicted_blocks += 1
        self._index_dirty = True

    def _object_id(self, content_hash: int) -> ObjectID:
        digest = hashlib.blake2b(
            f"kvtier:{self.engine_key}:{content_hash}".encode(),
            digest_size=16,
        ).digest()
        return ObjectID(digest)

    def _count_spill(self, tier: str, nbytes: int) -> None:
        try:
            from ray_tpu.llm.kvtier import metrics as kvtier_metrics

            kvtier_metrics.spilled_bytes_counter().inc(
                nbytes, tags={"model": self.engine.model_tag, "tier": tier}
            )
        except Exception:  # noqa: BLE001 — observability never breaks serving
            pass

    # -- resurrect path --------------------------------------------------------

    def peek(self, content_hash: int) -> Optional[str]:
        """Which deep tier holds this hash (read-only; no LRU motion)."""
        if content_hash in self._host:
            return TIER_HOST
        if content_hash in self._obj:
            return TIER_OBJECT
        return None

    def get(self, content_hash: int) -> Optional[tuple]:
        """(tier, SpilledBlock) without removing the entry — the caller
        commits with ``promoted`` only after the scatter landed."""
        sb = self._host.get(content_hash)
        if sb is not None:
            self._host.move_to_end(content_hash)
            return TIER_HOST, sb
        rec = self._obj.get(content_hash)
        if rec is not None:
            from ray_tpu.core.object_store import deserialize

            oid = rec[0]
            try:
                payload, buffers = self._store.serialized_get(oid, timeout=1.0)
                sb = deserialize(payload, buffers)
            except Exception:  # noqa: BLE001 — torn store entry = miss
                self._drop_entry(content_hash, TIER_OBJECT)
                return None
            self._obj.move_to_end(content_hash)
            return TIER_OBJECT, sb
        return None

    def take_verified(self, content_hash: int,
                      expect_tokens: tuple) -> Optional[tuple]:
        """(tier, SpilledBlock) iff the seal verifies AND the stored
        tokens match the prompt's block — a corrupt or hash-colliding
        entry is dropped and counted, and the caller recomputes from
        this block on (never wrong tokens)."""
        got = self.get(content_hash)
        if got is None:
            return None
        tier, sb = got
        ok = False
        try:
            ok = tuple(sb.tokens) == tuple(expect_tokens) and sb.handoff.verify()
        except Exception:  # noqa: BLE001 — malformed entry = corrupt
            ok = False
        if not ok:
            self.corrupt_dropped[tier] += 1
            self._drop_entry(content_hash, tier)
            try:
                from ray_tpu.llm.kvtier import metrics as kvtier_metrics

                kvtier_metrics.corrupt_dropped_counter().inc(
                    1, tags={"model": self.engine.model_tag, "tier": tier}
                )
            except Exception:  # noqa: BLE001
                pass
            logger.warning(
                "kvtier: dropped corrupt %s-tier block (chain %x); "
                "falling back to recompute", tier, content_hash & 0xFFFFFFFF,
            )
            return None
        return tier, sb

    def promoted(self, content_hash: int, tier: str) -> None:
        """The block is back in HBM (resurrected + re-registered): drop
        the deep-tier copy; the seal listener re-advertises it as hbm."""
        self._drop_entry(content_hash, tier)

    def count_resurrected(self, tier: str, n_tokens: int) -> None:
        self.resurrected_tokens[tier] = (
            self.resurrected_tokens.get(tier, 0) + n_tokens
        )
        try:
            from ray_tpu.llm.kvtier import metrics as kvtier_metrics

            kvtier_metrics.resurrected_tokens_counter().inc(
                n_tokens, tags={"model": self.engine.model_tag, "tier": tier}
            )
        except Exception:  # noqa: BLE001
            pass

    def _drop_entry(self, content_hash: int, tier: str) -> None:
        if tier == TIER_HOST:
            sb = self._host.pop(content_hash, None)
            if sb is not None:
                self._host_bytes -= sb.nbytes
        else:
            rec = self._obj.pop(content_hash, None)
            if rec is not None:
                self._obj_bytes -= rec[1]
                self._store.remove_ref(rec[0])
        self._index_dirty = True

    # -- probes (read-only; the routing signal) --------------------------------

    def probe_tiers(self, tokens: list, salt: int = 0) -> dict:
        """Longest contiguous resurrectable prefix of ``tokens`` across
        ALL tiers, tier-discounted. Read-only: no refs, no LRU motion.
        Returns {"n_tokens", "discounted", "by_tier": {tier: tokens}}."""
        from ray_tpu.llm.kv_cache import BlockAllocator

        alloc = self.engine.allocator
        bs = alloc.block_size
        c = self.config
        h = salt
        n = 0
        discounted = 0.0
        by_tier: dict[str, int] = {}
        for i in range(len(tokens) // bs):
            blk = tuple(tokens[i * bs : (i + 1) * bs])
            h = BlockAllocator.chain_hash(h, blk)
            if alloc.contains_hash(h):
                tier = TIER_HBM
            else:
                tier = self.peek(h)
                if tier is None:
                    break
            n += bs
            discounted += c.weight(tier) * bs
            by_tier[tier] = by_tier.get(tier, 0) + bs
        return {"n_tokens": n, "discounted": discounted, "by_tier": by_tier}

    # -- invalidation ----------------------------------------------------------

    def invalidate_all(self) -> None:
        """Weight swap / adapter churn: every tier's cached K/V is stale.
        Drops host + object entries, forgets HBM metadata, and ships an
        EMPTY index snapshot so the cluster stops routing here for
        prefixes this engine no longer holds."""
        self._meta.clear()
        self._host.clear()
        self._host_bytes = 0
        for oid, _n, _p, _np_ in self._obj.values():
            try:
                self._store.remove_ref(oid)
            except Exception:  # noqa: BLE001
                pass
        self._obj.clear()
        self._obj_bytes = 0
        self._index_dirty = True
        self.flush_index(force=True)

    # -- prefix-index publishing ----------------------------------------------

    def attach_index(self, index: Any, engine_key: Optional[str] = None) -> None:
        self.index = index
        if engine_key is not None:
            self.engine_key = engine_key
        self._index_dirty = True
        self.flush_index(force=True)

    # silent publishers' rows are omitted from lookups at the store's
    # stale_after_s and reaped past its expire horizon, so an engine in
    # steady state (nothing sealing or evicting) must still re-publish
    # on this heartbeat — it also repopulates a restarted GCS
    INDEX_REFRESH_S = 10.0

    def flush_index(self, force: bool = False) -> None:
        """Ship a full snapshot of resident chain hashes (throttled;
        called from the engine's telemetry refresh). Full snapshots +
        (epoch, seq) guarding give telemetry-style staleness semantics:
        a delayed re-send can never resurrect rows a newer snapshot
        dropped. A failed publish re-arms the dirty flag so the next
        throttle tick retries instead of going silent."""
        if self.index is None:
            return
        now = time.monotonic()
        due = self._index_dirty or now >= self._index_refresh_next
        if not force and (not due or now < self._index_next):
            return
        self._index_next = now + self.config.index_flush_interval_s
        self._index_refresh_next = now + self.INDEX_REFRESH_S
        rows = []
        for h, (_p, _tokens, n_prefix) in self._meta.items():
            rows.append([h, TIER_CODES[TIER_HBM], n_prefix])
        for h, sb in self._host.items():
            rows.append([h, TIER_CODES[TIER_HOST], sb.n_prefix_tokens])
        for h, (_oid, _n, _parent, n_prefix) in self._obj.items():
            rows.append([h, TIER_CODES[TIER_OBJECT], n_prefix])
        self._seq += 1
        self._index_dirty = False
        ok = False
        try:
            got = self.index.update({
                "engine": self.engine_key,
                "epoch": self._epoch,
                "seq": self._seq,
                "rows": rows,
            })
            # GcsPrefixIndex returns a bool; the store returns {"ok": ...}.
            # A "stale" verdict is NOT a failure to retry — it means a
            # newer snapshot (ours: seq only moves forward) already landed.
            ok = bool(got) if not isinstance(got, dict) else bool(got.get("ok"))
            if isinstance(got, dict) and got.get("reason") == "stale":
                ok = True
        except Exception:  # noqa: BLE001 — a dark index costs freshness only
            ok = False
        if not ok:
            self._index_dirty = True

    # -- observability ---------------------------------------------------------

    def update_gauges(self) -> None:
        try:
            from ray_tpu.llm.kvtier import metrics as kvtier_metrics

            g = kvtier_metrics.resident_bytes_gauge()
            tag = {"model": self.engine.model_tag}
            g.set(self._host_bytes, tags={**tag, "tier": TIER_HOST})
            g.set(self._obj_bytes, tags={**tag, "tier": TIER_OBJECT})
        except Exception:  # noqa: BLE001
            pass

    def stats(self) -> dict:
        return {
            "host": {
                "entries": len(self._host),
                "resident_bytes": self._host_bytes,
                "capacity_bytes": self.config.host_bytes,
            },
            "object": {
                "entries": len(self._obj),
                "resident_bytes": self._obj_bytes,
                "capacity_bytes": self.config.object_bytes,
            },
            "spilled_bytes_total": dict(self.spilled_bytes),
            "resurrected_tokens": dict(self.resurrected_tokens),
            "corrupt_dropped": dict(self.corrupt_dropped),
            "spills_dropped": self.spills_dropped,
            "evicted_blocks": self.evicted_blocks,
            "index_attached": self.index is not None,
            "engine_key": self.engine_key,
        }
