"""Cluster-level prefix index: chain hash -> {engine, tier, n_tokens}.

Three faces of one table:

 * ``PrefixIndexStore`` — the GCS-resident store behind the
   ``kvtier_update`` / ``kvtier_lookup`` RPCs. It lives in
   ``cluster/prefix_index.py`` (re-exported here) so the GCS process
   never imports the serving stack; see that module for the
   epoch/seq staleness discipline. Deliberately NOT persisted: like
   telemetry, the index is a freshness surface — a restarted GCS
   repopulates within one flush interval, and routing falls back to
   the queue-depth ladder until it does.
 * ``LocalPrefixIndex`` — the in-process store (single-host serving,
   CI): same update/lookup contract, shared through a process-global
   namespace registry so serve replicas and their ingress meet on it.
 * ``GcsPrefixIndex`` — the RPC client wrapper routers use. Every call
   is bounded and failure-swallowed: a dark or stalled GCS (r13
   STALL_GCS chaos) makes ``lookup`` return None — "no information" —
   so the caller's existing p2c/queue-depth ladder takes over with no
   hang and no wrong-replica pin.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ray_tpu.cluster.prefix_index import TIER_CODES, TIER_NAMES, PrefixIndexStore
from ray_tpu.llm.kvtier.config import KVTierConfig
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.llm.kvtier.index")


def chain_hashes(tokens: list, block_size: int, salt: int = 0) -> list:
    """The prefix-chain hash after each FULL block of ``tokens`` —
    the keys a prompt probes the index with. Mirrors
    BlockAllocator.chain_hash so index keys and cache keys can never
    drift."""
    from ray_tpu.llm.kv_cache import BlockAllocator

    out = []
    h = salt
    for i in range(len(tokens) // block_size):
        blk = tuple(tokens[i * block_size : (i + 1) * block_size])
        h = BlockAllocator.chain_hash(h, blk)
        out.append(h)
    return out


class LocalPrefixIndex(PrefixIndexStore):
    """Same store, shared in-process (serve replicas + ingress)."""


_LOCAL_LOCK = threading.Lock()
_LOCAL: dict[str, LocalPrefixIndex] = {}


def get_local_index(namespace: str) -> LocalPrefixIndex:
    """Process-global namespace registry: every party naming the same
    namespace (an app, an orchestrator) meets on one index."""
    with _LOCAL_LOCK:
        idx = _LOCAL.get(namespace)
        if idx is None:
            idx = _LOCAL[namespace] = LocalPrefixIndex()
        return idx


class GcsPrefixIndex:
    """RPC-backed index client. ``gcs`` is a ReconnectingRpcClient
    (r13: its gcs.call hook is where STALL_GCS chaos injects) — every
    call here is bounded by ``timeout_s`` and failure-swallowed, so a
    control-plane blackout costs routing FRESHNESS, never liveness."""

    def __init__(self, gcs: Any, timeout_s: float = 2.0):
        self._gcs = gcs
        self.timeout_s = timeout_s
        self.num_dark = 0  # calls answered by a dark/stalled index

    def update(self, payload: dict) -> bool:
        try:
            got = self._gcs.call("kvtier_update", payload,
                                 timeout=self.timeout_s)
            return bool(got and got.get("ok"))
        except Exception:  # noqa: BLE001 — the next snapshot supersedes
            self.num_dark += 1
            return False

    def lookup(self, hashes: list) -> Optional[dict]:
        try:
            return self._gcs.call("kvtier_lookup", {"hashes": list(hashes)},
                                  timeout=self.timeout_s)
        except Exception:  # noqa: BLE001 — dark index = no information
            self.num_dark += 1
            return None

    def drop_engine(self, engine: str) -> bool:
        """Orderly removal via the dedicated RPC — never by publishing a
        poisoned epoch, which would block a restarted engine reusing the
        key from ever registering again."""
        try:
            self._gcs.call("kvtier_drop", {"engine": engine},
                           timeout=self.timeout_s)
            return True
        except Exception:  # noqa: BLE001
            self.num_dark += 1
            return False


def best_prefix_replica(
    lookup: Optional[dict],
    depths: dict,
    cfg: Optional[KVTierConfig] = None,
    key_of: Optional[dict] = None,
    fetch_weight: float = 0.0,
) -> Optional[str]:
    """Tier-discounted routing pick over an index ``lookup`` result.

    ``depths`` maps replica -> queue depth for every LIVE candidate;
    ``key_of`` maps replica -> index engine key when they differ.
    Returns the replica to prefer, or None when the index is dark,
    holds nothing for this prompt, or the only holders are overloaded
    past ``depth_slack`` — in every None case the caller's existing
    queue-depth/p2c ladder decides (graceful degradation, never a pin).

    ``fetch_weight`` > 0 adds the r18 FETCH-COST discount: a replica
    that holds nothing itself scores ``fetch_weight`` times the best
    fresh holder's score — a pull over the fetch plane beats recompute
    but loses to any local copy. With the holder loaded past the depth
    slack, the pick now SPREADS to a cold within-slack replica that
    will fetch the prefix, instead of piling onto (or abandoning) the
    one hot holder.
    """
    if not lookup or not depths:
        return None
    cfg = cfg or KVTierConfig()
    engines = lookup.get("engines") or {}
    if not engines:
        return None
    min_depth = min(depths.values())

    def held_score(replica) -> float:
        key = (key_of or {}).get(replica, replica)
        got = engines.get(key)
        if got is None or got.get("age_s", 0.0) > cfg.index_stale_after_s:
            return 0.0
        return cfg.weight(got.get("tier")) * float(got.get("n_tokens", 0))

    # the fetch discount prices pulling from the best FRESH holder,
    # whether or not that holder is a routable candidate here
    best_held = 0.0
    if fetch_weight > 0.0:
        for got in engines.values():
            if got.get("age_s", 0.0) > cfg.index_stale_after_s:
                continue
            s = cfg.weight(got.get("tier")) * float(got.get("n_tokens", 0))
            best_held = max(best_held, s)
    best: Optional[tuple] = None
    for replica, depth in depths.items():
        if depth > min_depth + cfg.depth_slack:
            continue  # cache affinity must not overload one replica
        score = max(held_score(replica), fetch_weight * best_held)
        if score <= 0.0:
            continue
        cand = (score, -depth, replica)
        if best is None or cand > best:
            best = cand
    return best[-1] if best else None


__all__ = [
    "PrefixIndexStore",
    "LocalPrefixIndex",
    "GcsPrefixIndex",
    "get_local_index",
    "chain_hashes",
    "best_prefix_replica",
    "TIER_CODES",
    "TIER_NAMES",
]
