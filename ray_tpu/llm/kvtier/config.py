"""KVTierConfig: shape of one engine's tiered prefix cache."""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

# tier names + index wire codes are owned by the control-plane half
# (cluster/prefix_index.py — the GCS hosts the table without importing
# the serving stack); re-exported here for engine-side callers
from ray_tpu.cluster.prefix_index import (  # noqa: F401
    TIER_CODES,
    TIER_HBM,
    TIER_HOST,
    TIER_NAMES,
    TIER_OBJECT,
)


@dataclasses.dataclass
class KVTierConfig:
    """Budgets + routing weights for the HBM -> host -> object ladder.

    A tier with a zero budget is disabled; blocks falling past the last
    enabled tier are discarded (exactly the pre-kvtier behavior). The
    ``tier_weights`` discount what a cached prefix is worth to the
    router per tier: resurrecting from the object store still beats a
    recompute, but an HBM hit costs nothing at all, so routing must
    prefer the replica holding the prefix in the cheapest tier.
    """

    # host DRAM LRU budget for spilled page arrays (bytes; 0 disables)
    host_bytes: int = 64 << 20
    # object-store tier budget (bytes; 0 disables). Entries are
    # serialized through core/object_store.py — the plasma-shaped
    # boundary a multi-process deployment would cross.
    object_bytes: int = 256 << 20
    # optional shared ObjectStore instance (defaults to a private one);
    # entries are namespaced by engine key either way
    object_store: Any = None
    # routing discount per tier (missing tier = 0.0: never preferred)
    tier_weights: tuple = ((TIER_HBM, 1.0), (TIER_HOST, 0.6), (TIER_OBJECT, 0.35))
    # prefix-aware picks only prefer a prefix-holder whose queue depth
    # is within this slack of the least-loaded candidate — cache
    # affinity must not pile every request onto one hot replica
    depth_slack: int = 4
    # min seconds between full index snapshots shipped to the prefix
    # index (piggybacks on the engine's throttled telemetry refresh)
    index_flush_interval_s: float = 0.2
    # index rows older than this are treated as dark by routing helpers
    index_stale_after_s: float = 30.0

    # -- r18 (ray_tpu.llm.kvfetch) --------------------------------------------
    # async batched spill: eviction only captures the block's pages as
    # a device slice; a spill worker coalesces queued blocks into one
    # batched device->host gather off the allocation hot path. False
    # restores the r17 blocking gather (the bench's A/B baseline).
    async_spill: bool = True
    # bounded pending-spill queue (each entry pins its device slices);
    # overflow drops the oldest capture — a counted miss, never growth
    spill_queue_depth: int = 64
    # prefetch-at-admission: while a request waits in the queue, a
    # bounded worker verifies/deserializes its local deep-tier prefix
    # and pulls remote blocks over the fetch plane, so _prefill_one
    # finds the blocks already resident. False = r17 synchronous
    # resurrection only.
    prefetch: bool = True
    prefetch_queue_depth: int = 64
    # routing discount for a prefix held by ANOTHER engine this replica
    # can fetch from (must stay below every holding-tier weight: a pull
    # over the fabric beats recompute but loses to any local copy)
    fetch_weight: float = 0.25
    # bound on one cross-engine pull (typed KVFetchError past it: the
    # requester degrades to local tiers + recompute, never hangs)
    fetch_timeout_s: float = 5.0
    # cap on blocks pulled per fetch (one queue-waiting request)
    fetch_max_blocks: int = 64

    def weight(self, tier: Optional[str]) -> float:
        for t, w in self.tier_weights:
            if t == tier:
                return float(w)
        return 0.0
