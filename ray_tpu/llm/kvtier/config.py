"""KVTierConfig: shape of one engine's tiered prefix cache."""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

# tier names + index wire codes are owned by the control-plane half
# (cluster/prefix_index.py — the GCS hosts the table without importing
# the serving stack); re-exported here for engine-side callers
from ray_tpu.cluster.prefix_index import (  # noqa: F401
    TIER_CODES,
    TIER_HBM,
    TIER_HOST,
    TIER_NAMES,
    TIER_OBJECT,
)


@dataclasses.dataclass
class KVTierConfig:
    """Budgets + routing weights for the HBM -> host -> object ladder.

    A tier with a zero budget is disabled; blocks falling past the last
    enabled tier are discarded (exactly the pre-kvtier behavior). The
    ``tier_weights`` discount what a cached prefix is worth to the
    router per tier: resurrecting from the object store still beats a
    recompute, but an HBM hit costs nothing at all, so routing must
    prefer the replica holding the prefix in the cheapest tier.
    """

    # host DRAM LRU budget for spilled page arrays (bytes; 0 disables)
    host_bytes: int = 64 << 20
    # object-store tier budget (bytes; 0 disables). Entries are
    # serialized through core/object_store.py — the plasma-shaped
    # boundary a multi-process deployment would cross.
    object_bytes: int = 256 << 20
    # optional shared ObjectStore instance (defaults to a private one);
    # entries are namespaced by engine key either way
    object_store: Any = None
    # routing discount per tier (missing tier = 0.0: never preferred)
    tier_weights: tuple = ((TIER_HBM, 1.0), (TIER_HOST, 0.6), (TIER_OBJECT, 0.35))
    # prefix-aware picks only prefer a prefix-holder whose queue depth
    # is within this slack of the least-loaded candidate — cache
    # affinity must not pile every request onto one hot replica
    depth_slack: int = 4
    # min seconds between full index snapshots shipped to the prefix
    # index (piggybacks on the engine's throttled telemetry refresh)
    index_flush_interval_s: float = 0.2
    # index rows older than this are treated as dark by routing helpers
    index_stale_after_s: float = 30.0

    def weight(self, tier: Optional[str]) -> float:
        for t, w in self.tier_weights:
            if t == tier:
                return float(w)
        return 0.0
