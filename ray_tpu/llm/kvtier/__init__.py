"""ray_tpu.llm.kvtier — cluster-wide tiered KV/prefix cache.

The HBM prefix cache (llm/kv_cache.py BlockAllocator) is tier 0 of a
three-deep ladder:

    HBM (paged device cache)  ->  host DRAM (bounded LRU of page arrays)
        ->  object store (core/object_store.py, serialized + bounded)

Sealed full blocks evicted from the HBM allocator under allocation
pressure SPILL down the ladder instead of being discarded; a later
prompt sharing the prefix RESURRECTS them with a verified scatter
(import_handoff-shaped: the pages go straight back into the paged
cache, ``num_cached_tokens`` covers every resurrected position, zero
recompute). Every spilled block is CRC-sealed via the r10 ``KVHandoff``
seal machinery, so a corrupt host/object copy fails ``verify()`` and
falls back to recompute — counted, never wrong tokens.

A cluster-level prefix index (``index.PrefixIndexStore`` in the GCS,
``LocalPrefixIndex`` in-process) maps chain hashes to
{engine, tier, n_tokens} so the serve router and the disagg
orchestrator can route each request to the replica already holding its
longest prefix, tier-discounted (an HBM hit outranks an object-store
hit outranks a miss), falling back to the existing queue-depth/p2c
ladder whenever the index is dark or stale.
"""

from ray_tpu.llm.kvtier.config import KVTierConfig, TIER_HBM, TIER_HOST, TIER_OBJECT
from ray_tpu.llm.kvtier.index import (
    GcsPrefixIndex,
    LocalPrefixIndex,
    PrefixIndexStore,
    chain_hashes,
    get_local_index,
)
from ray_tpu.llm.kvtier.tiers import KVTierManager, SpilledBlock

__all__ = [
    "KVTierConfig",
    "KVTierManager",
    "SpilledBlock",
    "PrefixIndexStore",
    "LocalPrefixIndex",
    "GcsPrefixIndex",
    "get_local_index",
    "chain_hashes",
    "TIER_HBM",
    "TIER_HOST",
    "TIER_OBJECT",
]
