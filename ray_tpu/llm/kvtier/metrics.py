"""KV-tier observability: the series `ray_tpu status` renders in its
``== kv tiers ==`` block and /v1/stats breaks down per tier.

Construct-per-call like obs/slo.py and fabric/metrics.py (same-name
re-registration shares storage in util/metrics, so a test's
``clear_registry()`` can never strand a stale cached instance). All
series are telemetry-plane (``llm_`` is in
``obs.telemetry.AGGREGATED_PREFIXES``) and declare their aggregation
kinds, so ``check_metrics`` / ``check_aggregations`` hold them to the
same contract as every other cluster-rolled metric.

The per-tier prefix-cache HIT accounting itself lives on the existing
``llm_prefix_cache_hit_tokens_total`` counter (llm/engine.py), which
r17 splits by a ``tier`` label — hbm / host / object — so the fleet
hit rate and its tier mix come from ONE series family.
"""

from __future__ import annotations


def spilled_bytes_counter():
    """Bytes of sealed KV pages spilled DOWN the ladder, by destination
    tier (host = evicted from HBM into host DRAM, object = demoted from
    host into the object store). Counters aggregate by SUM."""
    from ray_tpu.obs.telemetry import cluster_counter

    return cluster_counter(
        "llm_kvtier_spilled_bytes_total",
        description="KV page bytes spilled from the HBM prefix cache "
        "into a deeper tier (labelled by destination tier)",
        tag_keys=("model", "tier"),
    )


def resident_bytes_gauge():
    """Bytes of spilled KV pages currently resident per deep tier
    (host/object). SUM across engines: the fleet value is the total
    spilled-cache footprint."""
    from ray_tpu.obs.telemetry import cluster_gauge

    return cluster_gauge(
        "llm_kvtier_resident_bytes",
        description="KV page bytes currently held by this engine's "
        "host-DRAM / object-store prefix-cache tiers",
        tag_keys=("model", "tier"),
    )


def resurrected_tokens_counter():
    """Prompt tokens resurrected back into HBM with zero recompute, by
    source tier."""
    from ray_tpu.obs.telemetry import cluster_counter

    return cluster_counter(
        "llm_kvtier_resurrected_tokens_total",
        description="prompt tokens whose KV was resurrected into the "
        "paged cache from a deeper tier (no recompute), by source tier",
        tag_keys=("model", "tier"),
    )


def corrupt_dropped_counter():
    """Spilled blocks whose CRC/token check failed at resurrection —
    dropped and recomputed, never decoded from garbage pages."""
    from ray_tpu.obs.telemetry import cluster_counter

    return cluster_counter(
        "llm_kvtier_corrupt_dropped_total",
        description="spilled KV blocks dropped because seal "
        "verification failed at resurrection (fell back to recompute)",
        tag_keys=("model", "tier"),
    )


def register_metrics() -> None:
    """scripts/check_metrics.py hook: force lazy metrics to register."""
    spilled_bytes_counter()
    resident_bytes_gauge()
    resurrected_tokens_counter()
    corrupt_dropped_counter()
