"""Batch LLM inference over ray_tpu.data datasets.

Reference analog: python/ray/llm/_internal/batch/ (Processor +
processor stages riding Ray Data). Here the processor is a
`Dataset.map_batches` stage holding one engine per worker: rows in,
rows + generated text out, continuous batching inside the stage so the
chip stays busy across the whole block, not per-row.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ray_tpu.llm.engine import EngineConfig, LLMEngine
from ray_tpu.llm.openai_api import ByteTokenizer, default_chat_template
from ray_tpu.llm.sampling import SamplingParams


@dataclass
class ProcessorConfig:
    """Reference analog: vLLMEngineProcessorConfig (batch/processor/)."""

    engine: EngineConfig = field(default_factory=EngineConfig)
    tokenizer: Any = None
    params: Any = None
    sampling: SamplingParams = field(default_factory=SamplingParams)
    prompt_column: str = "prompt"
    messages_column: Optional[str] = None  # chat mode if set
    output_column: str = "generated_text"
    seed: int = 0
    batch_size: int = 64


class _EngineStage:
    """Callable class for map_batches: one engine per worker, reused
    across blocks (the reference keeps one vLLM engine per actor)."""

    def __init__(self, config: ProcessorConfig):
        self.config = config
        self.tokenizer = config.tokenizer or ByteTokenizer(
            config.engine.model.vocab_size
        )
        config.engine.eos_token_id = getattr(self.tokenizer, "eos_token_id", 2)
        self.engine = LLMEngine(config.engine, params=config.params, seed=config.seed)

    def __call__(self, batch: dict) -> dict:
        cfg = self.config
        if cfg.messages_column is not None:
            prompts = [
                default_chat_template(m) for m in batch[cfg.messages_column]
            ]
        else:
            prompts = [str(p) for p in batch[cfg.prompt_column]]
        ids = [self.tokenizer.encode(p) for p in prompts]
        outs = self.engine.generate(ids, cfg.sampling)
        texts = []
        eos = self.engine.config.eos_token_id
        for toks in outs:
            if toks and toks[-1] == eos:
                toks = toks[:-1]
            texts.append(self.tokenizer.decode(toks))
        out = dict(batch)
        out[cfg.output_column] = texts
        return out


def build_processor(config: ProcessorConfig) -> Callable:
    """Returns dataset -> dataset (reference: build_llm_processor)."""

    def apply(dataset):
        return dataset.map_batches(
            _EngineStage,
            fn_constructor_args=(config,),
            batch_size=config.batch_size,
            concurrency=1,
        )

    return apply
