"""Single-dispatch pipelined decode: device-resident batch state,
on-device stop masks, async double-buffered chunks, adaptive chunk
length.

The r06 decode profile prices ``sampling`` (~36%) and ``host_sync`` as
the dominant non-matmul segments of a decode step, and the r08 traces
show every chunk round-trip ending in a blocking ``np.asarray`` sync
plus a full rebuild + re-upload of the batch arrays from numpy. This
module removes all four taxes from the serving hot loop:

 * **DeviceBatchState** — tokens / positions / context_lens / block
   tables / sampling knobs / PRNG keys / stop sets live ON DEVICE
   across chunks and are re-materialized only at membership changes
   (join / finish / preempt / import_handoff), not every round;
 * **on-device stop masks** — ``decode_chunk_masked`` carries a per-row
   ``done`` mask folding EOS, bounded stop-id sets, max_tokens and the
   max_seq wall in-graph: finished rows freeze (trash-slot KV writes,
   masked sampling outputs, no position advance past the RoPE table)
   and a ``lax.while_loop`` early-out stops the whole chunk once every
   row is done — a batch that finishes at step 1 of a 16-step chunk
   does not pay the other 15;
 * **async double-buffered dispatch** — the engine dispatches chunk
   N+1 from the device-resident carry BEFORE syncing chunk N's tokens
   (JAX async dispatch), so host-side detokenize / stop bookkeeping /
   SLO spans / admission overlap device compute;
 * **ChunkController** — chunk length is driven from the measured
   per-round host gap and per-step device time, quantized to
   CHUNK_BUCKETS so the engine's jit cache stays bounded, replacing
   the hand-picked ``decode_chunk=8/16``.

Correctness contract: the pipelined path produces bitwise-identical
token streams to the sync path (greedy and seeded sampling, including
stop-token and max_tokens terminations) — sampling keys remain a pure
function of (request key, absolute output index), and the host stop
ladder in ``_append_chunk`` walks exactly the per-row ``n_emitted``
tokens the device kept. "Exploring the limits of Concurrency in ML
Training on Google TPUs" (PAPERS.md) is the blueprint: hide host
latency behind device work and never let the host gate the chip.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.llm.sampling import sample_tokens
from ray_tpu.models.llama_decode import decode_step

# the ONLY chunk lengths the engine may compile: the adaptive controller
# quantizes into this set and LLMEngine asserts membership, so the
# (n_steps, mode) jit cache is bounded by construction instead of
# growing with every novel chunk length
CHUNK_BUCKETS = (1, 2, 4, 8, 16, 32, 64)

# stop-id sets are carried on device as a padded [B, stop_w] matrix;
# widths are bucketed (compile-shape bounding) and capped — a request
# with more stop ids than the cap falls back to the sync decode path
STOP_WIDTHS = (1, 2, 4, 8)
STOP_WIDTH_CAP = STOP_WIDTHS[-1]


def chunk_bucket(n: int, cap: Optional[int] = None) -> int:
    """Smallest CHUNK_BUCKETS entry >= n; with ``cap``, never larger
    than the smallest bucket covering the cap (steps past every row's
    budget are pure waste). Always a valid compile bucket."""
    pick = next((b for b in CHUNK_BUCKETS if b >= n), CHUNK_BUCKETS[-1])
    if cap is not None:
        capb = next(
            (b for b in CHUNK_BUCKETS if b >= max(1, cap)), CHUNK_BUCKETS[-1]
        )
        pick = min(pick, capb)
    return pick


def stop_width(n: int) -> int:
    """Smallest STOP_WIDTHS entry >= max(1, n); caller must have
    checked n <= STOP_WIDTH_CAP."""
    for w in STOP_WIDTHS:
        if w >= max(1, n):
            return w
    raise ValueError(
        f"stop set width {n} exceeds STOP_WIDTH_CAP={STOP_WIDTH_CAP}"
    )


# ---------------------------------------------------------------------------
# observability: host/device time split histograms + /v1/stats row
# ---------------------------------------------------------------------------

_host_prep_hist = None
_sync_wait_hist = None

_SPLIT_BOUNDARIES = [0.05, 0.1, 0.25, 0.5, 1, 2, 5, 10, 25, 50, 100, 500]


def host_prep_histogram():
    """Host-side prep ms per pipelined round (state refresh + KV
    reservation + dispatch) — the work the double-buffered dispatch
    hides under device compute. Beside llm_decode_chunk_ms it makes the
    overlap win measurable per-round, not just end-to-end."""
    global _host_prep_hist
    if _host_prep_hist is None:
        from ray_tpu.util.metrics import Histogram

        _host_prep_hist = Histogram(
            "llm_decode_host_prep_ms",
            description="profiler: host ms per pipelined decode round "
            "spent preparing + dispatching the next chunk (overlapped "
            "with the in-flight chunk's device compute)",
            boundaries=_SPLIT_BOUNDARIES,
        )
    return _host_prep_hist


def sync_wait_histogram():
    global _sync_wait_hist
    if _sync_wait_hist is None:
        from ray_tpu.util.metrics import Histogram

        _sync_wait_hist = Histogram(
            "llm_decode_sync_wait_ms",
            description="profiler: host ms per pipelined decode round "
            "blocked in the device->host token sync (the un-hidden "
            "remainder of the round trip)",
            boundaries=_SPLIT_BOUNDARIES,
        )
    return _sync_wait_hist


def register_metrics() -> None:
    """scripts/check_metrics.py hook: force lazy metrics to register."""
    host_prep_histogram()
    sync_wait_histogram()


def record_host_prep(ms: float) -> None:
    try:
        host_prep_histogram().observe(ms)
    except Exception:  # noqa: BLE001 — observability must not break decode
        pass


def record_sync_wait(ms: float) -> None:
    try:
        sync_wait_histogram().observe(ms)
    except Exception:  # noqa: BLE001
        pass


@dataclasses.dataclass
class PipelineStats:
    """Pipelined-decode counters for the ``pipeline`` row of
    ``/v1/stats`` (the serving-side view, no Prometheus scrape needed):
    chunk-size distribution, host/device time split, overlap ratio, and
    the device steps the early-out actually skipped."""

    dispatches: int = 0
    syncs: int = 0
    rebuilds: int = 0
    flushes: int = 0
    sync_fallbacks: int = 0           # wide-stop-set batches
    steps_dispatched: int = 0         # sum of n_steps over chunks
    steps_executed: int = 0           # sum of while_loop exits (early-out)
    host_prep_ms: float = 0.0         # overlapped host work
    sync_wait_ms: float = 0.0         # un-hidden sync block
    chunk_ms: float = 0.0             # dispatch -> sync wall
    chunks_by_steps: dict = dataclasses.field(default_factory=dict)

    def record_dispatch(self, n_steps: int, host_prep_ms: float) -> None:
        self.dispatches += 1
        self.steps_dispatched += n_steps
        self.host_prep_ms += host_prep_ms
        self.chunks_by_steps[n_steps] = self.chunks_by_steps.get(n_steps, 0) + 1

    def record_sync(self, *, steps_run: int, sync_wait_ms: float,
                    chunk_ms: float) -> None:
        self.syncs += 1
        self.steps_executed += steps_run
        self.sync_wait_ms += sync_wait_ms
        self.chunk_ms += chunk_ms

    @property
    def overlap_ratio(self) -> float:
        """Fraction of per-round host time hidden under device compute:
        prep / (prep + un-hidden sync wait)."""
        total = self.host_prep_ms + self.sync_wait_ms
        return self.host_prep_ms / total if total > 0 else 0.0

    def to_dict(self) -> dict:
        return {
            "dispatches": self.dispatches,
            "syncs": self.syncs,
            "rebuilds": self.rebuilds,
            "flushes": self.flushes,
            "sync_fallbacks": self.sync_fallbacks,
            "chunks_by_steps": dict(sorted(self.chunks_by_steps.items())),
            "steps_dispatched": self.steps_dispatched,
            "steps_executed": self.steps_executed,
            "steps_saved_by_early_exit": max(
                0, self.steps_dispatched - self.steps_executed
            ),
            "host_prep_ms": round(self.host_prep_ms, 3),
            "sync_wait_ms": round(self.sync_wait_ms, 3),
            "chunk_ms": round(self.chunk_ms, 3),
            "overlap_ratio": round(self.overlap_ratio, 4),
        }


# ---------------------------------------------------------------------------
# adaptive chunk length
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ChunkController:
    """Measured-gap-adaptive chunk length (a ratchet, not a formula).

    The signal pair: per-round HOST OVERHEAD (the r08 ``sched_gap_ms``
    between a sync landing and the next dispatch, plus the un-hidden
    sync wait) versus the measured chunk wall (the
    ``llm_decode_chunk_ms`` histogram's observation). A chunk must be
    long enough that overhead hides under device compute with
    ``target_ratio`` headroom — when it isn't, step up one bucket. The
    only downward pressure is SYSTEMATIC early exit (the while_loop
    retiring under half the dispatched steps on consecutive chunks:
    the batch keeps finishing long before the chunk does, so shorter
    chunks cut reserved-KV churn at zero throughput cost).

    Deliberately NOT ``n = overhead/step_cost``: per-step cost measured
    at one chunk length conflates fixed dispatch overhead with marginal
    step cost and collapses to 1-step chunks on hosts where dispatch
    dominates — the exact regime chunking exists to amortize.

    The decision is a pure function of the fed measurements
    (EMA-smoothed), so a fixed gap/chunk trace replays to a
    deterministic bucket sequence, and every output is quantized to
    CHUNK_BUCKETS so the engine's jit cache stays bounded."""

    initial: int = 8
    target_ratio: float = 2.0
    alpha: float = 0.3                 # EMA smoothing
    shrink_frac: float = 0.5           # early-exit threshold
    shrink_patience: int = 2           # consecutive short chunks to shrink
    chunk_ms_ema: Optional[float] = None
    overhead_ms_ema: Optional[float] = None
    _level: Optional[int] = None       # index into CHUNK_BUCKETS
    _short_rounds: int = 0

    def _lvl(self) -> int:
        if self._level is None:
            self._level = CHUNK_BUCKETS.index(chunk_bucket(max(1, self.initial)))
        return self._level

    def note_overhead(self, ms: float) -> None:
        ms = max(0.0, float(ms))
        self.overhead_ms_ema = (
            ms if self.overhead_ms_ema is None
            else (1 - self.alpha) * self.overhead_ms_ema + self.alpha * ms
        )

    def note_chunk(self, chunk_ms: float, n_steps: int,
                   steps_run: Optional[int] = None) -> None:
        if n_steps <= 0 or chunk_ms <= 0:
            return
        self.chunk_ms_ema = (
            chunk_ms if self.chunk_ms_ema is None
            else (1 - self.alpha) * self.chunk_ms_ema + self.alpha * chunk_ms
        )
        lvl = self._lvl()
        if (
            self.overhead_ms_ema is not None
            and self.chunk_ms_ema < self.target_ratio * self.overhead_ms_ema
        ):
            # device work too short to hide the host round: step up
            self._level = min(lvl + 1, len(CHUNK_BUCKETS) - 1)
            self._short_rounds = 0
            return
        if steps_run is not None and steps_run < self.shrink_frac * n_steps:
            self._short_rounds += 1
            if self._short_rounds >= self.shrink_patience:
                self._level = max(lvl - 1, 0)
                self._short_rounds = 0
        else:
            self._short_rounds = 0

    def next_steps(self, cap: Optional[int] = None) -> int:
        """Chunk length for the next dispatch, in CHUNK_BUCKETS.
        ``cap`` bounds it (e.g. the batch's largest remaining token
        budget — steps past every row's budget are pure waste)."""
        return chunk_bucket(CHUNK_BUCKETS[self._lvl()], cap)


# ---------------------------------------------------------------------------
# device-resident batch state
# ---------------------------------------------------------------------------


def assemble_batch_arrays(batch: list, B_pad: int, bt_width: int):
    """Per-row decode-batch assembly: the SINGLE source of truth for
    how a Request becomes batch-array rows (fed token, position,
    context length, sampling knobs, key, absolute output index, block
    table). Both the sync path (LLMEngine._plain_decode_step) and
    DeviceBatchState.build consume this — the pipelined-vs-sync bitwise
    token-identity contract depends on the two paths never drifting,
    so neither keeps its own copy.

    Returns (arrays dict of np arrays, keys list of per-request PRNG
    keys). Pad rows: context_lens 0 (the kernels' pad/done signal),
    temperature 1, top_p 1, max_tokens INT32_MAX, key(0)."""
    a = {
        "tokens": np.zeros(B_pad, np.int32),
        "positions": np.zeros(B_pad, np.int32),
        "context_lens": np.zeros(B_pad, np.int32),
        "lora_ids": np.zeros(B_pad, np.int32),
        "temps": np.ones(B_pad, np.float32),
        "top_ks": np.zeros(B_pad, np.int32),
        "top_ps": np.ones(B_pad, np.float32),
        "starts": np.zeros(B_pad, np.int32),
        "max_toks": np.full(B_pad, np.iinfo(np.int32).max, np.int32),
        "bt": np.zeros((B_pad, bt_width), np.int32),
    }
    keys = [jax.random.key(0)] * B_pad
    for i, r in enumerate(batch):
        sp = r.sampling_params
        a["tokens"][i] = (
            r.output_token_ids[-1] if r.output_token_ids
            else r.prompt_token_ids[-1]
        )
        a["positions"][i] = r.num_tokens - 1  # position of the fed token
        a["context_lens"][i] = r.num_tokens
        a["lora_ids"][i] = r.lora_slot
        a["temps"][i] = sp.temperature
        a["top_ks"][i] = sp.top_k
        a["top_ps"][i] = sp.top_p
        a["starts"][i] = len(r.output_token_ids)
        a["max_toks"][i] = sp.max_tokens
        a["bt"][i, : len(r.seq.blocks)] = r.seq.blocks
        keys[i] = r._key
    return a, keys


@dataclasses.dataclass
class DeviceBatchState:
    """The decode batch, resident on device across chunks.

    Built once per membership change (the old per-round numpy rebuild +
    ``jnp.asarray``/``jnp.stack`` upload, amortized); between chunks
    only the carry (tokens / positions / context_lens / done / starts)
    is swapped — device arrays returned by the previous chunk, no host
    transfer — and the block-table mirror re-uploads only when a row
    actually grew. Rows that finish keep their column as permanently
    ``done`` rows (trash-slot writes, zero emissions) until the next
    rebuild, which is what lets chunk N+1 dispatch before chunk N's
    finishes are even known host-side."""

    rids: list
    row_of: dict
    B: int
    B_pad: int
    bt_width: int
    stop_w: int
    sample_mode: str
    # device-resident carry (updated from each chunk's return)
    tokens: Any = None
    positions: Any = None
    context_lens: Any = None
    done: Any = None
    starts: Any = None
    # device-resident per-request constants
    temps: Any = None
    top_ks: Any = None
    top_ps: Any = None
    keys: Any = None
    max_toks: Any = None
    stop_ids: Any = None
    stop_on_eos: Any = None
    lora_ids: Any = None
    block_tables: Any = None
    # host mirrors (block-table refresh without a device round trip)
    _bt_np: Any = None
    _nblocks: list = dataclasses.field(default_factory=list)

    @classmethod
    def build(cls, engine, batch: list) -> "DeviceBatchState":
        c = engine.config
        B = len(batch)
        B_pad = engine._pad_to_bucket(B, c.decode_buckets())
        btw = engine._bt_width([len(r.seq.blocks) for r in batch])
        sw = stop_width(max(
            (len(r.sampling_params.stop_token_ids) for r in batch), default=0
        ))
        a, keys = assemble_batch_arrays(batch, B_pad, btw)
        # pipeline-only rows the sync path evaluates host-side instead:
        # the padded stop-id sets and the per-row EOS policy
        stop_ids = np.full((B_pad, sw), -1, np.int32)
        stop_on_eos = np.zeros(B_pad, bool)
        nblocks = [0] * B_pad
        for i, r in enumerate(batch):
            sp = r.sampling_params
            for j, t in enumerate(sp.stop_token_ids[:sw]):
                stop_ids[i, j] = t
            stop_on_eos[i] = not sp.ignore_eos
            nblocks[i] = len(r.seq.blocks)
        rids = [r.request_id for r in batch]
        return cls(
            rids=rids,
            row_of={rid: i for i, rid in enumerate(rids)},
            B=B, B_pad=B_pad, bt_width=btw, stop_w=sw,
            sample_mode=engine._sample_mode(batch),
            tokens=jnp.asarray(a["tokens"]),
            positions=jnp.asarray(a["positions"]),
            context_lens=jnp.asarray(a["context_lens"]),
            done=jnp.zeros(B_pad, bool),
            starts=jnp.asarray(a["starts"]),
            temps=jnp.asarray(a["temps"]),
            top_ks=jnp.asarray(a["top_ks"]),
            top_ps=jnp.asarray(a["top_ps"]),
            keys=jnp.stack(keys),
            max_toks=jnp.asarray(a["max_toks"]),
            stop_ids=jnp.asarray(stop_ids),
            stop_on_eos=jnp.asarray(stop_on_eos),
            lora_ids=jnp.asarray(a["lora_ids"]),
            block_tables=jnp.asarray(a["bt"]),
            _bt_np=a["bt"],
            _nblocks=nblocks,
        )

    def adopt_carry(self, carry) -> None:
        """Swap in the device arrays a chunk returned (no host sync)."""
        (self.tokens, self.positions, self.context_lens,
         self.done, self.starts) = carry

    def refresh_block_tables(self, running: list) -> bool:
        """Fold newly-allocated blocks into the device table; uploads
        the (small) table only when a row actually changed. Returns
        False when a row outgrew the padded width (caller rebuilds)."""
        dirty = False
        for r in running:
            i = self.row_of.get(r.request_id)
            if i is None or r.seq is None:
                continue
            nb = len(r.seq.blocks)
            if nb != self._nblocks[i]:
                if nb > self.bt_width:
                    return False
                self._bt_np[i, :nb] = r.seq.blocks
                self._nblocks[i] = nb
                dirty = True
        if dirty:
            self.block_tables = jnp.asarray(self._bt_np)
        return True


# ---------------------------------------------------------------------------
# the masked, early-exiting decode chunk
# ---------------------------------------------------------------------------


def decode_chunk_masked(
    params,
    tokens: jax.Array,        # [B] current tokens (carry)
    positions: jax.Array,     # [B] absolute positions of `tokens` (carry)
    block_tables: jax.Array,  # [B, MB]
    context_lens: jax.Array,  # [B] INCLUDING the current token (carry)
    cache,
    temperatures: jax.Array,  # [B]
    top_ks: jax.Array,        # [B]
    top_ps: jax.Array,        # [B]
    keys: jax.Array,          # [B] STABLE per-request PRNG keys
    starts: jax.Array,        # [B] absolute output index of step 0's token
    max_toks: jax.Array,      # [B] max_tokens budget (absolute)
    done: jax.Array,          # [B] bool carry: row already finished
    stop_ids: jax.Array,      # [B, S] stop-token sets, -1 padded
    stop_on_eos: jax.Array,   # [B] bool: EOS finishes the row (~ignore_eos)
    config,
    *,
    n_steps: int,
    block_size: int,
    trash_slot: int,
    eos_id: int,
    attn_impl: str = "auto",
    sample_mode: str = "full",
    lora=None,
):
    """Decode up to ``n_steps`` tokens with the stop ladder IN-GRAPH.

    Returns ``(tokens [n_steps, B], logprobs [n_steps, B],
    n_emitted [B], steps_run scalar, carry, cache)`` where carry is the
    next chunk's ``(tokens, positions, context_lens, done, starts)``.

    Per-row semantics match the host ladder in
    ``LLMEngine._append_chunk`` exactly: a token is emitted, THEN the
    row goes done if it was EOS (unless ignored), in the stop set, hit
    max_tokens, or hit the model's max_seq wall. Done rows freeze —
    trash-slot KV writes, no position/context advance (the RoPE table
    is never indexed past max_seq), masked 0-token/0-logprob outputs,
    same PRNG fold (unused) — so a chunk dispatched before the host
    even knows who finished still computes the identical stream for
    live rows. ``lax.while_loop`` exits once every row (pads included)
    is done: the all-done early-out."""
    B = tokens.shape[0]
    rows = jnp.arange(B)
    done0 = done | (context_lens <= 0)  # pad rows are born done

    toks_buf = jnp.zeros((n_steps, B), jnp.int32)
    lps_buf = jnp.zeros((n_steps, B), jnp.float32)
    n_emit0 = jnp.zeros(B, jnp.int32)

    def cond(carry):
        s, _tok, _pos, _ctx, dn, _ne, _tb, _lb, _cache = carry
        return (s < n_steps) & ~jnp.all(dn)

    def body(carry):
        s, tok, pos, ctx, dn, ne, tb, lb, cache = carry
        active = ~dn
        # slot for the fed token straight from the block table; done and
        # pad rows write the trash page, never block 0
        slot = (
            block_tables[rows, pos // block_size] * block_size
            + pos % block_size
        )
        slot = jnp.where(active, slot, trash_slot)
        logits, cache = decode_step(
            params, tok, pos, slot, block_tables, ctx, cache, config,
            block_size=block_size, attn_impl=attn_impl, lora=lora,
        )
        # key = fold(request key, absolute output index): identical to
        # the sync path for every live row, chunk partitioning invariant
        step_keys = jax.vmap(jax.random.fold_in)(keys, starts + s)
        nxt, lp = sample_tokens(
            logits, temperatures, top_ks, top_ps, step_keys,
            mode=sample_mode, done=dn,
        )
        ne2 = ne + active.astype(jnp.int32)
        # stop ladder, same conditions/threshold as _append_chunk
        hit_stop = jnp.any(stop_ids == nxt[:, None], axis=-1)
        hit_eos = stop_on_eos & (nxt == eos_id)
        hit_len = (starts + ne2) >= max_toks
        hit_seq = (ctx + 1) >= config.max_seq
        dn2 = dn | (active & (hit_eos | hit_stop | hit_len | hit_seq))
        tb = tb.at[s].set(jnp.where(active, nxt, 0))
        lb = lb.at[s].set(jnp.where(active, lp, 0.0))
        # frozen once done: token/position/context stop advancing
        tok2 = jnp.where(active, nxt, tok)
        pos2 = jnp.where(active, pos + 1, pos)
        ctx2 = jnp.where(active, ctx + 1, ctx)
        return (s + 1, tok2, pos2, ctx2, dn2, ne2, tb, lb, cache)

    (steps_run, tok, pos, ctx, dn, n_emit, toks_buf, lps_buf, cache) = (
        jax.lax.while_loop(
            cond, body,
            (jnp.asarray(0, jnp.int32), tokens, positions, context_lens,
             done0, n_emit0, toks_buf, lps_buf, cache),
        )
    )
    carry = (tok, pos, ctx, dn, starts + n_emit)
    return toks_buf, lps_buf, n_emit, steps_run, carry, cache
