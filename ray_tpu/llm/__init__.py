"""ray_tpu.llm: TPU-native LLM inference.

Where the reference delegates model execution to vLLM inside placement
groups (python/ray/llm/_internal/serve/deployments/llm/vllm/), this is a
native engine: paged KV cache with prefix reuse, continuous batching,
jitted sampling, an OpenAI-compatible Serve app, and Ray-Data-style
batch inference. See SURVEY.md §2.5 (Ray LLM) and §7 L4.
"""

from ray_tpu.llm.batch import ProcessorConfig, build_processor
from ray_tpu.llm.disagg import DisaggConfig
from ray_tpu.llm.engine import EngineConfig, LLMEngine, Request, RequestOutput
from ray_tpu.llm.kv_cache import BlockAllocator, KVCacheConfig
from ray_tpu.llm.kvtier import KVTierConfig
from ray_tpu.llm.openai_api import ByteTokenizer, LLMConfig, LLMServer, build_openai_app
from ray_tpu.llm.sampling import SamplingParams
from ray_tpu.llm.spec import SpecConfig

__all__ = [
    "BlockAllocator",
    "ByteTokenizer",
    "DisaggConfig",
    "EngineConfig",
    "KVCacheConfig",
    "KVTierConfig",
    "LLMConfig",
    "LLMEngine",
    "LLMServer",
    "ProcessorConfig",
    "Request",
    "RequestOutput",
    "SamplingParams",
    "SpecConfig",
    "build_openai_app",
    "build_processor",
]
