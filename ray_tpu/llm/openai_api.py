"""OpenAI-compatible LLM serving on ray_tpu.serve.

Reference analogs: python/ray/llm/_internal/serve/builders/
application_builders.py (build_openai_app), configs/openai_api_models.py
(request/response schemas), deployments/llm/vllm/vllm_deployment.py.
Here the deployment hosts the native engine (llm/engine.py) with a
dedicated engine-loop thread doing continuous batching; requests are
asyncio futures resolved as the loop emits tokens.

Endpoints: /v1/models, /v1/completions, /v1/chat/completions
(stream=true returns a complete SSE transcript; token-level streaming
is available via serve handles — get_app_handle(...).options(stream=True)),
/v1/stats, and the request-tracing surface (ray_tpu.obs): /v1/requests
(flight-recorder listing) + /v1/requests/{id}/trace (per-request span
tree with TTFT/TPOT/queue-wait and span-coverage honesty). Completion
payloads carry the trace_id.
"""

from __future__ import annotations

import asyncio
import json
import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ray_tpu import obs
from ray_tpu.llm.engine import EngineConfig, LLMEngine, RequestOutput
from ray_tpu.llm.sampling import SamplingParams


from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.llm.openai_api")


def _noop() -> None:
    """Release placeholder for rejected admissions (nothing reserved)."""


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------


class ByteTokenizer:
    """Self-contained fallback tokenizer: UTF-8 bytes + specials. Lets the
    stack run hermetically (no downloaded vocabulary); swap in any object
    with encode/decode/eos_token_id for a real model."""

    PAD, BOS, EOS = 0, 1, 2
    OFFSET = 3

    def __init__(self, vocab_size: int = 512):
        self.vocab_size = vocab_size
        self.eos_token_id = self.EOS

    def encode(self, text: str) -> list:
        return [self.BOS] + [
            min(b + self.OFFSET, self.vocab_size - 1) for b in text.encode()
        ]

    def decode(self, ids: list) -> str:
        bs = bytes(
            i - self.OFFSET for i in ids if self.OFFSET <= i < 256 + self.OFFSET
        )
        return bs.decode(errors="replace")


def default_chat_template(messages: list) -> str:
    """Minimal chat rendering (role-tagged turns + assistant cue)."""
    parts = []
    for m in messages:
        parts.append(f"<|{m['role']}|>\n{m['content']}\n")
    parts.append("<|assistant|>\n")
    return "".join(parts)


# ---------------------------------------------------------------------------
# engine runner: continuous-batching loop + per-request output queues
# ---------------------------------------------------------------------------


class _EngineRunner:
    """Continuous-batching loop + per-request output queues + crash
    recovery.

    Delivery is gated by a per-request ``delivered`` counter over the
    request's FULL output prefix (not the engine's per-round
    new_token_ids): after a crash the engine re-enqueues in-flight
    requests and recomputes their prefix (LLMEngine.recover), so the
    completion id stays idempotent — consumers see each output position
    exactly once, never a lost or duplicated token, whatever the engine
    died and recovered underneath them."""

    # recovery budget: more than MAX_RECOVERIES engine deaths inside
    # RECOVERY_WINDOW_S is a crash loop, not a preemption — fail loudly
    MAX_RECOVERIES = 3
    RECOVERY_WINDOW_S = 30.0

    def __init__(self, engine: LLMEngine, engine_factory=None):
        self.engine = engine
        self._engine_factory = engine_factory  # full-rebuild fallback
        self.lock = threading.Lock()
        self._queues: dict[str, queue.Queue] = {}
        # rid -> {"prompt_ids", "sp", "trace", "delivered"}: enough to
        # re-create the request on a fresh engine AND to dedupe delivery
        self._inflight: dict[str, dict] = {}
        self._recoveries: list[float] = []
        self.num_recoveries = 0
        self._wake = threading.Event()
        self._stop = False
        self._dead: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._loop, name="llm-engine-loop", daemon=True
        )
        self._thread.start()

    def submit(
        self,
        prompt_ids: list,
        sp: SamplingParams,
        request_id: Optional[str] = None,
        trace=None,
        **add_kwargs,
    ) -> tuple[str, queue.Queue]:
        """``add_kwargs`` pass through to ``engine.add_request`` (the
        fleet plane rides lora_id / priority / tenant / slo_tag here)
        and are replayed by the full-rebuild recovery rung."""
        q: queue.Queue = queue.Queue()
        with self.lock:
            # checked under the lock: the death handler drains _queues under
            # it, so an insert after the drain would hang its caller forever
            if self._dead is not None:
                raise RuntimeError(
                    f"engine loop died: {self._dead!r}"
                ) from self._dead
            rid = self.engine.add_request(
                prompt_ids, sp, request_id=request_id, trace=trace,
                **add_kwargs,
            )
            self._queues[rid] = q
            # "tokens" holds the DELIVERED output prefix (not just a
            # count): the full-rebuild recovery rung seeds the fresh
            # engine's request with it, so even unseeded sampling can
            # never splice two different continuations
            self._inflight[rid] = {
                "prompt_ids": list(prompt_ids), "sp": sp, "trace": trace,
                "tokens": [], "kwargs": dict(add_kwargs),
            }
        self._wake.set()
        return rid, q

    def abort(self, rid: str) -> None:
        with self.lock:
            self.engine.abort_request(rid)
            q = self._queues.pop(rid, None)
            self._inflight.pop(rid, None)
        if q is not None:
            q.put(None)

    def _deliver(self, out: RequestOutput) -> None:
        """Queue-put with idempotent delivery: only output positions past
        the per-request delivered watermark ship."""
        import dataclasses as _dc

        q = self._queues.get(out.request_id)
        rec = self._inflight.get(out.request_id)
        if rec is not None:
            new = list(out.output_token_ids[len(rec["tokens"]):])
            rec["tokens"].extend(new)
            out = _dc.replace(out, new_token_ids=new)
        if q is None:
            return
        if out.new_token_ids or out.finished:
            q.put(out)
        if out.finished:
            self._queues.pop(out.request_id, None)
            self._inflight.pop(out.request_id, None)

    def _loop(self) -> None:
        while not self._stop:
            with self.lock:
                busy = self.engine.has_unfinished()
            if not busy:
                self._wake.wait(timeout=0.2)
                self._wake.clear()
                continue
            try:
                with self.lock:
                    outputs = self.engine.step()
                    for out in outputs:
                        self._deliver(out)
            except BaseException as e:  # a wedged step must not hang callers
                if not self._stop and self._try_recover(e):
                    continue
                logger.exception(
                    "engine loop failed; failing all in-flight requests"
                )
                self._dead = e
                with self.lock:
                    queues = list(self._queues.values())
                    self._queues.clear()
                    self._inflight.clear()
                for q in queues:
                    q.put(e)
                return

    def _try_recover(self, exc: BaseException) -> bool:
        """Engine crash/preemption recovery ladder: (1) requeue in-flight
        requests on the surviving engine (clean preemption), (2) requeue
        with a rebuilt KV cache (unknown crash), (3) fresh engine from the
        factory with every request re-created (engine object torn).
        Bounded by the recovery budget so a deterministic crash loop still
        fails fast."""
        now = time.time()
        self._recoveries = [
            t for t in self._recoveries if now - t < self.RECOVERY_WINDOW_S
        ]
        if len(self._recoveries) >= self.MAX_RECOVERIES:
            return False
        self._recoveries.append(now)
        self.num_recoveries += 1
        try:
            from ray_tpu.chaos.harness import EnginePreempted

            clean = isinstance(exc, EnginePreempted)
        except Exception:  # noqa: BLE001
            clean = False
        t0 = time.time()
        requeued: Optional[list] = None
        try:
            with self.lock:
                requeued = self.engine.recover(rebuild_kv=not clean)
        except BaseException:  # noqa: BLE001 — engine object itself is torn
            logger.exception("engine.recover failed; trying full rebuild")
            if self._engine_factory is None:
                return False
            try:
                with self.lock:
                    old = self.engine
                    self.engine = self._engine_factory()
                    self.engine.model_tag = old.model_tag
                    # re-create every in-flight request on the fresh
                    # engine WITH its delivered prefix restored: admission
                    # prefills prompt + outputs (the preemption-recompute
                    # contract), so the continuation extends exactly what
                    # the consumer already received — not a fresh sample
                    # spliced at the watermark
                    for rid, rec in self._inflight.items():
                        self.engine.add_request(
                            rec["prompt_ids"], rec["sp"], request_id=rid,
                            trace=rec["trace"], **rec.get("kwargs", {}),
                        )
                        self.engine.requests[rid].output_token_ids = list(
                            rec["tokens"]
                        )
                    requeued = list(self._inflight)
            except BaseException:  # noqa: BLE001
                logger.exception("engine rebuild failed")
                return False
        logger.warning(
            "engine loop recovered from %r (%d request(s) re-enqueued)",
            exc, len(requeued or ()),
        )
        try:
            from ray_tpu import obs

            obs.get_recorder().record(
                "engine.runner_recover", t0, time.time(),
                attrs={"cause": f"{type(exc).__name__}: {exc}"[:200],
                       "requeued": len(requeued or ()),
                       "clean_preemption": clean},
                status="error",
            )
        except Exception:  # noqa: BLE001
            pass
        self._wake.set()
        return True

    def shutdown(self) -> None:
        self._stop = True
        self._wake.set()


# ---------------------------------------------------------------------------
# the deployment
# ---------------------------------------------------------------------------


@dataclass
class LLMConfig:
    """Reference analog: ray.llm LLMConfig (server_models.py)."""

    model_id: str = "llama-tiny"
    engine: EngineConfig = field(default_factory=EngineConfig)
    tokenizer: Any = None  # encode/decode/eos_token_id; ByteTokenizer default
    params: Any = None     # model weights pytree; random-init if None
    seed: int = 0
    # admission control / load shedding (llm/admission.py); None = an
    # unbounded controller that still supports graceful drain
    admission: Any = None
    # disaggregated prefill/decode (llm/disagg): a DisaggConfig (or dict)
    # replaces the single engine with prefill+decode pools behind the
    # same OpenAI surface; its .engine defaults to `engine` above
    disagg: Any = None


class LLMServer:
    """Serve deployment hosting one engine (reference: VLLMDeployment)."""

    def __init__(self, config: LLMConfig):
        from ray_tpu.llm.admission import AdmissionConfig, AdmissionController

        self.config = config
        self.tokenizer = config.tokenizer or ByteTokenizer(
            config.engine.model.vocab_size
        )
        config.engine.eos_token_id = getattr(self.tokenizer, "eos_token_id", 2)
        self.orchestrator = None
        self.runner = None
        if config.disagg is not None:
            # disaggregated mode: prefill+decode pools replace the single
            # engine; submit/abort/stats route through the orchestrator
            from ray_tpu.llm.disagg import DisaggConfig, DisaggOrchestrator

            dcfg = config.disagg
            if isinstance(dcfg, dict):
                dcfg = DisaggConfig(**{"engine": config.engine, **dcfg})
            self.orchestrator = DisaggOrchestrator(
                dcfg, params=config.params, seed=config.seed,
                model_tag=config.model_id,
            )
        else:
            engine = LLMEngine(
                config.engine, params=config.params, seed=config.seed
            )
            engine.model_tag = config.model_id  # SLO histogram label

            def _rebuild_engine():
                # crash-recovery fallback: fresh engine, same weights/seed
                return LLMEngine(config.engine, params=config.params,
                                 seed=config.seed)

            self.runner = _EngineRunner(engine, engine_factory=_rebuild_engine)
        acfg = config.admission
        if isinstance(acfg, dict):
            acfg = AdmissionConfig(**acfg)
        # admission reservation state: see _admission_check
        self._admit_lock = threading.Lock()
        self._admit_reserved = 0
        self.admission = AdmissionController(
            acfg or AdmissionConfig(), model_tag=config.model_id
        )

    @property
    def engine(self) -> LLMEngine:
        if self.orchestrator is not None:
            # config access (eos, max_seq) — pools share one EngineConfig
            return self.orchestrator._decode[0].engine
        # via the runner: crash recovery may have swapped in a rebuilt one
        return self.runner.engine

    def __del__(self):
        try:
            self._stop_engines()
        except Exception:
            pass

    def _stop_engines(self):
        if self.orchestrator is not None:
            self.orchestrator.shutdown()
        if self.runner is not None:
            self.runner.shutdown()

    def shutdown(self):
        """Replica graceful-shutdown hook (serve.replica.prepare_shutdown
        calls this after its own in-flight drain): stop admission, give
        the engine a short drain, stop the loop."""
        try:
            self.drain(timeout_s=5.0)
        finally:
            self._stop_engines()

    def drain(self, timeout_s: float = 30.0) -> dict:
        """Maintenance-event drain: new requests get 503 + Retry-After
        while in-flight requests run to completion (bounded wait)."""
        self.admission.start_drain()
        deadline = time.time() + timeout_s
        if self.orchestrator is not None:
            while time.time() < deadline and self.orchestrator.has_unfinished():
                time.sleep(0.05)
            # count the orchestrator's inflight set, not engine queue
            # depths: a handoff in transit sits on NO engine, and a drain
            # that misses it reports clean while losing the request
            left = self.orchestrator.num_inflight()
            return {"drained": left == 0, "inflight": left}
        while time.time() < deadline:
            with self.runner.lock:
                if not self.engine.has_unfinished():
                    break
            time.sleep(0.05)
        with self.runner.lock:
            left = len(self.engine.waiting) + len(self.engine.running)
        return {"drained": left == 0, "inflight": left}

    # -- request plumbing -----------------------------------------------------

    def _sampling_from_body(self, body: dict) -> SamplingParams:
        return SamplingParams(
            max_tokens=int(body.get("max_tokens", 64)),
            temperature=float(body.get("temperature", 1.0)),
            top_k=int(body.get("top_k", 0)),
            top_p=float(body.get("top_p", 1.0)),
            seed=body.get("seed"),
            logprobs=bool(body.get("logprobs", False)),
        )

    async def _run(self, prompt_ids: list, sp: SamplingParams,
                   request_id: Optional[str] = None,
                   on_enqueued: Optional[Callable[[], None]] = None):
        """Async generator of RequestOutput. The ambient TraceContext is
        captured HERE (the caller's asyncio task) and handed to the
        engine explicitly — the engine loop is a separate thread where
        the contextvar is invisible."""
        loop = asyncio.get_running_loop()
        try:
            if self.orchestrator is not None:
                rid, q = self.orchestrator.submit(
                    prompt_ids, sp, request_id=request_id, trace=obs.current()
                )
                aborter = self.orchestrator.abort
            else:
                rid, q = self.runner.submit(
                    prompt_ids, sp, request_id=request_id, trace=obs.current()
                )
                aborter = self.runner.abort
        finally:
            # the admission reservation hands over to the real queue entry
            # here (or dies with a failed submit) — never held past this
            if on_enqueued is not None:
                on_enqueued()
        try:
            while True:
                out: Optional[RequestOutput] = await loop.run_in_executor(None, q.get)
                if out is None:
                    return
                if isinstance(out, BaseException):  # engine loop died
                    raise RuntimeError("engine loop failed") from out
                yield out
                if out.finished:
                    return
        finally:
            aborter(rid)

    async def _generate_text(self, prompt_ids: list, sp: SamplingParams,
                             request_id: Optional[str] = None,
                             on_enqueued: Optional[Callable[[], None]] = None):
        toks, reason = [], None
        async for out in self._run(prompt_ids, sp, request_id=request_id,
                                   on_enqueued=on_enqueued):
            toks = out.output_token_ids
            reason = out.finish_reason
        # strip eos token from the visible text
        if toks and toks[-1] == self.engine.config.eos_token_id:
            toks = toks[:-1]
        return self.tokenizer.decode(toks), toks, reason

    # -- handle-level streaming (token deltas) --------------------------------

    async def generate_stream(self, prompt: str, **kwargs):
        """Async generator of text deltas (serve streaming handles).

        Admission applies here too: a draining/overloaded server must not
        keep admitting via the streaming side door (that would hold
        has_unfinished() true and make every drain time out). Streams
        can't return an error payload, so rejection raises."""
        rej, admit_done = self._admission_check()
        if rej is not None:
            err = rej["error"]
            raise RuntimeError(
                f"admission rejected ({err['code']}): {err['message']}; "
                f"retry after {err['retry_after']}s"
            )
        try:
            sp = self._sampling_from_body(kwargs)
            ids = self.tokenizer.encode(prompt)
        except BaseException:
            admit_done()  # the reservation must not outlive a dead arrival
            raise
        try:
            async for delta in self._stream_deltas(ids, sp, admit_done):
                yield delta
        finally:
            # idempotent backstop: covers a generator abandoned before its
            # first iteration ever reached _run's submit (fires on close/GC)
            admit_done()

    async def _stream_deltas(self, ids, sp, admit_done):
        sent = ""
        first_mark = False
        async for out in self._run(ids, sp, on_enqueued=admit_done):
            toks = out.output_token_ids
            if toks and toks[-1] == self.engine.config.eos_token_id:
                toks = toks[:-1]
            text = self.tokenizer.decode(toks)
            # hold back a trailing replacement char: it's usually half of a
            # multi-byte sequence whose tail arrives with the next token
            if not out.finished:
                text = text.rstrip("�")
            if text.startswith(sent) and len(text) > len(sent):
                if not first_mark:
                    # streaming first-token mark: the client-visible TTFT
                    # point (engine TTFT excludes queue/decoding overhead
                    # this side of the loop thread)
                    first_mark = True
                    if obs.current() is not None:
                        now = time.time()
                        try:
                            obs.get_recorder().record(
                                "api.stream_first_token", now, now,
                                attrs={"tokens": len(toks)},
                            )
                        except Exception:  # noqa: BLE001
                            pass
                yield text[len(sent):]
                sent = text

    # -- HTTP surface ---------------------------------------------------------

    async def __call__(self, request):
        path, method = request.path, request.method
        if path.rstrip("/") == "/v1/models" and method == "GET":
            return self.models()
        if path.rstrip("/") == "/v1/stats" and method == "GET":
            return self.stats()
        if path.rstrip("/") == "/v1/requests" and method == "GET":
            return self.list_requests()
        parts = [p for p in path.split("/") if p]
        if (len(parts) == 4 and parts[:2] == ["v1", "requests"]
                and parts[3] == "trace" and method == "GET"):
            return self.request_trace(parts[2])
        if path.rstrip("/") == "/v1/completions" and method == "POST":
            return await self.completions(request.json())
        if path.rstrip("/") == "/v1/chat/completions" and method == "POST":
            return await self.chat_completions(request.json())
        if path.rstrip("/") == "/v1/drain" and method == "POST":
            # maintenance trigger: stop admission, finish in-flight work.
            # Off-loop: drain() polls synchronously for up to timeout_s,
            # and blocking the replica's event loop would freeze the very
            # in-flight responses the drain is waiting on (plus health
            # pings — the controller would kill a healthily-draining
            # replica)
            body = request.json() or {}
            timeout_s = float(body.get("timeout_s", 30.0))
            return await asyncio.get_running_loop().run_in_executor(
                None, lambda: self.drain(timeout_s=timeout_s)
            )
        return {"error": {"message": f"no route {method} {path}", "code": 404}}

    # -- flight recorder surface ----------------------------------------------

    def list_requests(self, limit: int = 100) -> dict:
        """Flight-recorder listing: the last N traced requests (newest
        first) with trace ids, root span, e2e, span counts."""
        rec = obs.get_recorder()
        return {
            "object": "list",
            "data": rec.traces(limit=limit),
            "dropped_traces": rec.num_dropped_traces,
            "dropped_spans": rec.num_dropped_spans,
        }

    # span cap for one trace response: a runaway generation's trace must
    # not build a response that blows past RPC framing / HTTP sanity
    TRACE_MAX_SPANS = 2048

    def request_trace(self, request_id: str, max_spans: Optional[int] = None) -> dict:
        """Full span tree for one request (by engine/completion request
        id, or directly by trace id), plus e2e + span-coverage honesty.
        Bounded: at most ``max_spans`` spans (earliest first) with an
        explicit ``truncated`` flag."""
        cap = self.TRACE_MAX_SPANS if max_spans is None else int(max_spans)
        rec = obs.get_recorder()
        trace_id = rec.find_by_request(request_id) or request_id
        spans = rec.get(trace_id)
        if not spans:
            return {"error": {
                "message": f"no recorded trace for request {request_id!r} "
                "(evicted from the flight recorder, or never traced)",
                "type": "not_found_error",
                "code": 404,
            }}
        summary = rec.summary(trace_id) or {}
        total = len(spans)
        truncated = total > cap
        if truncated:
            spans = sorted(spans, key=lambda s: s.start)[:cap]
        return {
            "request_id": request_id,
            "trace_id": trace_id,
            **{k: v for k, v in summary.items() if k != "trace_id"},
            "spans": [s.to_dict() for s in spans],
            "truncated": truncated,
            "total_spans": total,
        }

    def stats(self) -> dict:
        """Engine scheduling/KV state + (when speculative decoding is on)
        acceptance-rate stats — the serving-side view of
        LLMEngine.stats(), so operators can read draft quality (and in
        disaggregated mode the per-pool + transfer-plane picture, incl.
        the prefix-cache hit rate the decode pick consumes) without
        scraping Prometheus."""
        from ray_tpu.util.metrics import snapshot_meta

        if self.orchestrator is not None:
            out = {
                "model_id": self.config.model_id,
                "mode": "disagg",
                **self.orchestrator.stats(),
            }
            out["admission"] = self.admission.stats()
            # snapshot timestamp + process-epoch id (the telemetry plane's
            # restart-detection header; free here via the same API)
            out["telemetry"] = snapshot_meta()
            return out
        with self.runner.lock:
            out = {"model_id": self.config.model_id, **self.engine.stats()}
        out["admission"] = self.admission.stats()
        out["engine_recoveries"] = self.runner.num_recoveries
        out["telemetry"] = snapshot_meta()
        return out

    def _admission_check(self) -> tuple[Optional[dict], Callable[[], None]]:
        """Load-shedding decision for one arriving request.

        Returns ``(rejection, release)``. On admit (rejection None) a
        RESERVATION is counted against the queue depth until ``release()``
        runs (idempotent; _run fires it once the request is actually in
        the engine queue, the handler's finally is the backstop).
        Without the reservation, N concurrent arrivals could ALL pass the
        depth check before any of them enqueues — the check-then-enqueue
        race that let a 24-wide burst sail past max_queue_depth=3
        un-shed (caught tuning the overload chaos test)."""
        with self._admit_lock:
            if self.orchestrator is not None:
                depths = self.orchestrator.queue_depths()
                rej = self.admission.check(
                    num_waiting=sum(depths["prefill"]) + self._admit_reserved,
                    num_running=sum(depths["decode"]),
                )
            else:
                with self.runner.lock:
                    num_waiting = len(self.engine.waiting)
                    num_running = len(self.engine.running)
                rej = self.admission.check(
                    num_waiting=num_waiting + self._admit_reserved,
                    num_running=num_running,
                )
            if rej is not None:
                return rej, _noop
            self._admit_reserved += 1

        released = [False]

        def release() -> None:
            if not released[0]:
                released[0] = True
                with self._admit_lock:
                    self._admit_reserved -= 1

        return None, release

    def models(self) -> dict:
        return {
            "object": "list",
            "data": [
                {
                    "id": self.config.model_id,
                    "object": "model",
                    "owned_by": "ray_tpu",
                    "max_model_len": self.engine.config.model.max_seq,
                }
            ],
        }

    @staticmethod
    def _invalid_request(e: Exception) -> dict:
        """OpenAI-style error payload for bad sampling knobs: admission
        validation (SamplingParams) must surface as a client error, not
        an unhandled 500 from the serve layer."""
        return {
            "error": {
                "message": str(e),
                "type": "invalid_request_error",
                "code": 400,
            }
        }

    async def completions(self, body: dict) -> Any:
        rej, admit_done = self._admission_check()
        if rej is not None:
            return rej
        try:
            return await self._completions_admitted(body, admit_done)
        finally:
            # idempotent backstop: a no-op when _run already handed the
            # reservation to the engine queue; otherwise (parse error,
            # encode failure, empty prompt list) the reservation dies here
            admit_done()

    async def _completions_admitted(self, body: dict, admit_done) -> Any:
        try:
            sp = self._sampling_from_body(body)
        except (ValueError, TypeError) as e:
            return self._invalid_request(e)
        prompts = body.get("prompt", "")
        if not isinstance(prompts, list):
            prompts = [prompts]
        rid = f"cmpl-{uuid.uuid4().hex[:24]}"
        # request root span: engine request ids derive from the completion
        # id, so GET /v1/requests/{id}/trace resolves the whole trace
        with obs.span("api.completions", attrs={
            "request_id": rid,
            "model": body.get("model", self.config.model_id),
            "endpoint": "/v1/completions",
            "num_prompts": len(prompts),
        }) as ctx:
            id_lists = [self.tokenizer.encode(str(p)) for p in prompts]
            # one choice per prompt, generated concurrently via the engine;
            # the single admission reservation rides the first submit
            results = await asyncio.gather(
                *[
                    self._generate_text(
                        ids, sp,
                        request_id=rid if len(id_lists) == 1 else f"{rid}-{i}",
                        on_enqueued=admit_done if i == 0 else None,
                    )
                    for i, ids in enumerate(id_lists)
                ]
            )
            n_prompt = sum(len(ids) for ids in id_lists)
            n_out = sum(len(toks) for _, toks, _ in results)
            payload = {
                "id": rid,
                "object": "text_completion",
                "created": int(time.time()),
                "model": body.get("model", self.config.model_id),
                "trace_id": ctx.trace_id,
                "choices": [
                    {
                        "index": i,
                        "text": text,
                        "finish_reason": reason,
                        "logprobs": None,
                    }
                    for i, (text, _toks, reason) in enumerate(results)
                ],
                "usage": {
                    "prompt_tokens": n_prompt,
                    "completion_tokens": n_out,
                    "total_tokens": n_prompt + n_out,
                },
            }
        if body.get("stream"):
            return _sse_transcript(payload, "text_completion")
        return payload

    async def chat_completions(self, body: dict) -> Any:
        rej, admit_done = self._admission_check()
        if rej is not None:
            return rej
        try:
            return await self._chat_completions_admitted(body, admit_done)
        finally:
            admit_done()  # idempotent backstop, see completions()

    async def _chat_completions_admitted(self, body: dict, admit_done) -> Any:
        try:
            sp = self._sampling_from_body(body)
        except (ValueError, TypeError) as e:
            return self._invalid_request(e)
        messages = body.get("messages", [])
        rid = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        with obs.span("api.chat_completions", attrs={
            "request_id": rid,
            "model": body.get("model", self.config.model_id),
            "endpoint": "/v1/chat/completions",
        }) as ctx:
            prompt = default_chat_template(messages)
            ids = self.tokenizer.encode(prompt)
            text, toks, reason = await self._generate_text(
                ids, sp, request_id=rid, on_enqueued=admit_done
            )
            payload = {
                "id": rid,
                "object": "chat.completion",
                "created": int(time.time()),
                "model": body.get("model", self.config.model_id),
                "trace_id": ctx.trace_id,
                "choices": [
                    {
                        "index": 0,
                        "message": {"role": "assistant", "content": text},
                        "finish_reason": reason,
                    }
                ],
                "usage": {
                    "prompt_tokens": len(ids),
                    "completion_tokens": len(toks),
                    "total_tokens": len(ids) + len(toks),
                },
            }
        if body.get("stream"):
            return _sse_transcript(payload, "chat.completion.chunk")
        return payload


def _sse_transcript(payload: dict, obj: str) -> str:
    """Full-assembly SSE body (incremental HTTP streaming: see module doc)."""
    choice = payload["choices"][0]
    text = choice.get("text", choice.get("message", {}).get("content", ""))
    events = []
    chunk = dict(payload, object=obj)
    if obj.startswith("chat"):
        chunk = dict(chunk)
        chunk["choices"] = [
            {"index": 0, "delta": {"role": "assistant", "content": text},
             "finish_reason": choice["finish_reason"]}
        ]
    events.append(f"data: {json.dumps(chunk)}")
    events.append("data: [DONE]")
    return "\n\n".join(events) + "\n\n"


def build_openai_app(
    llm_config: LLMConfig,
    *,
    name: str = "llm",
    route_prefix: str = "/",
    num_replicas: int = 1,
    max_ongoing_requests: int = 64,
):
    """Deploy an OpenAI-compatible app; returns the ingress handle
    (reference: build_openai_app, application_builders.py)."""
    from ray_tpu import serve

    dep = serve.deployment(
        LLMServer,
        name=f"LLMServer:{llm_config.model_id}",
        num_replicas=num_replicas,
        max_ongoing_requests=max_ongoing_requests,
    )
    return serve.run(dep.bind(llm_config), name=name, route_prefix=route_prefix)
