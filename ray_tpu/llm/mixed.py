"""Mixed prefill+decode batching: ONE ragged dispatch per engine step.

The split engine runs prefill and decode as separate programs — every
admitted prompt pays its own bucket-padded dispatch while the decode
batch stalls behind it. The ragged kernel tier (ops/ragged.py +
models/llama_decode.ragged_forward) removes the reason for the split:
queries are PACKED variable-length rows, so one program serves a batch
mixing in-flight prefill chunks (q_len up to the per-step budget) and
decode rows (q_len = 1). This module is the planner that turns the
engine's running set into that packed program's arrays.

Discipline (LLMEngine._mixed_step):

 * Admission reuses the split path's ladder verbatim (_admit_one:
   prefix match, tier resurrection, capacity, accounting) but dispatches
   nothing — the request joins `running` with a prefill cursor in
   `engine._mixed_prefills` and its prompt streams through subsequent
   mixed dispatches, `mixed_prefill_chunk` tokens per step.
 * Every step that has prefill work packs ALL decode rows into the same
   dispatch — decode never starves behind a long prompt by
   construction, and each decode row advances one token per step.
 * A step with no prefill work is the degenerate all-q_len=1 case and
   routes to the existing decode ladder (spec / pipelined / chunked) at
   the current kernel's cost — mixed mode changes nothing when there is
   nothing to mix.

Token identity: decode rows sample with the same
fold_in(request key, absolute output index) keys, and the ragged
einsum structure mirrors the split kernels' reduction order, so the
mixed engine's token streams are BITWISE identical to the split
engine's (the split path is retained as the identity oracle; tests
assert it).
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["MixedBatchPlan", "MixedStats", "token_bucket"]


def token_bucket(n: int) -> int:
    """Packed-token-axis pad: the next power of two, floored at 16.
    Bounded by construction — T never exceeds
    max_num_seqs * mixed_prefill_chunk, so the compiled-shape set is
    the handful of powers of two up to that product."""
    return 1 << max(4, (max(1, n) - 1).bit_length())


@dataclasses.dataclass
class MixedStats:
    """Padding-waste accounting for the mixed dispatch path — the
    series the --mixed bench's padding_waste_ratio reads. packed =
    real fed tokens, padded = the T_pad bucket total they shipped in."""

    dispatches: int = 0
    packed_tokens: int = 0
    padded_tokens: int = 0
    prefill_tokens: int = 0
    decode_tokens: int = 0
    completed_prefills: int = 0

    @property
    def padding_waste(self) -> float:
        if not self.padded_tokens:
            return 0.0
        return 1.0 - self.packed_tokens / self.padded_tokens

    def to_dict(self) -> dict:
        return {
            "dispatches": self.dispatches,
            "packed_tokens": self.packed_tokens,
            "padded_tokens": self.padded_tokens,
            "prefill_tokens": self.prefill_tokens,
            "decode_tokens": self.decode_tokens,
            "completed_prefills": self.completed_prefills,
            "padding_waste_ratio": round(self.padding_waste, 4),
        }


@dataclasses.dataclass
class MixedBatchPlan:
    """One mixed dispatch's packed arrays + row bookkeeping.

    Row order: prefill rows (running order), then decode rows, then
    q_len-0 pad sequences up to the decode bucket. ``emit_rows`` are
    the rows whose last-position logits get sampled this step (decode
    rows + prefills whose final chunk lands); ``completes`` marks the
    finishing prefills among them."""

    reqs: list
    kinds: list            # "prefill" | "decode" per row
    starts: list           # prefill: chunk start; decode: fed position
    chunk_lens: list
    emit_rows: list
    completes: list
    tokens: np.ndarray       # [T_pad]
    positions: np.ndarray    # [T_pad]
    slots: np.ndarray        # [T_pad] (pad -> trash slot)
    lora_ids: np.ndarray     # [T_pad] per-TOKEN adapter slots
    cu_q_lens: np.ndarray    # [B_pad + 1]
    context_lens: np.ndarray # [B_pad]
    bt: np.ndarray           # [B_pad, W]
    T: int
    B: int

    @classmethod
    def build(cls, engine) -> "MixedBatchPlan":
        c = engine.config
        budget = max(1, c.mixed_prefill_chunk)
        rows = []  # (req, kind, start, chunk_len)
        for r in engine.running:
            start = engine._mixed_prefills.get(r.request_id)
            if start is not None:
                prompt_len = len(r.prompt_token_ids) + len(r.output_token_ids)
                rows.append((r, "prefill", start,
                             min(budget, prompt_len - start)))
        for r in engine.running:
            if r.request_id not in engine._mixed_prefills:
                rows.append((r, "decode", r.num_tokens - 1, 1))

        B = len(rows)
        B_pad = engine._pad_to_bucket(B, c.decode_buckets())
        T = sum(cl for *_x, cl in rows)
        T_pad = token_bucket(T)
        num_slots = c.num_blocks * c.block_size

        tokens = np.zeros(T_pad, np.int32)
        positions = np.zeros(T_pad, np.int32)
        slots = np.full(T_pad, num_slots, np.int32)  # trash by default
        lora_ids = np.zeros(T_pad, np.int32)
        cu = np.zeros(B_pad + 1, np.int32)
        ctx = np.zeros(B_pad, np.int32)
        bt = np.zeros(
            (B_pad,
             engine._bt_width([len(r.seq.blocks) for r, *_x in rows] or [1])),
            np.int32,
        )
        emit_rows, completes = [], []
        reqs, kinds, starts, chunk_lens = [], [], [], []
        t = 0
        for i, (r, kind, start, clen) in enumerate(rows):
            if kind == "prefill":
                prompt = r.prompt_token_ids + r.output_token_ids
                fed = prompt[start : start + clen]
                ctx[i] = start + clen
                if start + clen == len(prompt):
                    # final chunk: this row's last-position logits are
                    # the request's first-token distribution
                    emit_rows.append(i)
                    completes.append(i)
            else:
                fed = [
                    r.output_token_ids[-1] if r.output_token_ids
                    else r.prompt_token_ids[-1]
                ]
                ctx[i] = r.num_tokens
                emit_rows.append(i)
            tokens[t : t + clen] = fed
            positions[t : t + clen] = np.arange(start, start + clen)
            for j in range(clen):
                slots[t + j] = r.seq.slot(start + j)
            lora_ids[t : t + clen] = r.lora_slot
            bt[i, : len(r.seq.blocks)] = r.seq.blocks
            t += clen
            cu[i + 1] = t
            reqs.append(r)
            kinds.append(kind)
            starts.append(start)
            chunk_lens.append(clen)
        cu[B + 1 :] = t  # pad sequences: q_len 0, ctx 0

        return cls(
            reqs=reqs, kinds=kinds, starts=starts, chunk_lens=chunk_lens,
            emit_rows=emit_rows, completes=completes,
            tokens=tokens, positions=positions, slots=slots,
            lora_ids=lora_ids, cu_q_lens=cu, context_lens=ctx, bt=bt,
            T=T, B=B,
        )

    def note(self, stats: MixedStats) -> None:
        stats.dispatches += 1
        stats.packed_tokens += self.T
        stats.padded_tokens += len(self.tokens)
        stats.prefill_tokens += sum(
            cl for k, cl in zip(self.kinds, self.chunk_lens) if k == "prefill"
        )
        stats.decode_tokens += sum(
            cl for k, cl in zip(self.kinds, self.chunk_lens) if k == "decode"
        )
        stats.completed_prefills += len(self.completes)
