"""KVConnector: pluggable transfer plane for prefill->decode KV handoffs.

Three backends ship (the third lives in ``ray_tpu/fabric`` and builds
on this interface):

 * ``InProcessConnector`` — queue handoff inside one process (tests,
   CPU smoke, serve replicas which are in-process async actors). The
   object crosses by reference; integrity still goes through the same
   checksum gate so chaos corruption is exercised end to end.
 * ``RpcKVConnector`` — cluster transfer over the ``cluster/rpc.py``
   length-prefixed frame protocol: each decode target runs one shared
   RpcServer route (``kv_put_chunk``); prefill-side sends go through a
   ``ClientPool`` with bounded call timeouts, so a stalled decode host
   fails the transfer (-> re-prefill) instead of wedging the sender.
   Oversized handoffs chunk into seq-numbered multi-frame sends.
 * ``fabric.device_connector.DeviceKVConnector`` — the ICI/device-direct
   backend this interface was shaped for: ``register_target`` binds a
   device mesh endpoint, ``k_pages``/``v_pages`` move as device arrays
   (``jax.device_put`` — ICI DMA on TPU, device memcpy on CPU CI), and
   the same checksum/timeout failure modes surface; nothing in the
   orchestrator's failure handling changes.

Chaos: every send passes through the ``disagg.kv_transfer`` hook site —
``DROP_KV_TRANSFER`` raises ``KVTransferError`` before the send,
``CORRUPT_KV_TRANSFER`` bit-flips the KV pages (the receiver's
``verify()`` catches it at import), ``DELAY_RPC`` injects latency.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, Optional

import numpy as np

from ray_tpu.chaos import harness as _chaos
from ray_tpu.llm.disagg.handoff import KVHandoff
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.llm.disagg.connector")


class KVTransferError(Exception):
    """A handoff was dropped, timed out, or arrived corrupt. The
    orchestrator's answer is always the same: re-prefill elsewhere."""


def _corrupt_handoff(handoff: KVHandoff) -> KVHandoff:
    """Deterministic KV bit-flip (CORRUPT_KV_TRANSFER): flip a span of
    bytes in the middle of the K pages; the checksum is NOT re-sealed,
    so the receiver's verify() fails exactly like a real torn wire."""
    k = np.array(handoff.k_pages, copy=True)
    flat = k.view(np.uint8).reshape(-1)
    if flat.size:
        mid = flat.size // 2
        span = max(1, min(16, flat.size - mid))
        flat[mid : mid + span] ^= 0xFF
    return dataclasses.replace(handoff, k_pages=k)


class KVConnector:
    """Transfer-plane interface; see module docstring for the contract."""

    name = "base"

    def __init__(self):
        self.num_sent = 0
        self.num_received = 0
        self.num_dropped = 0
        self.bytes_sent = 0

    # -- interface ------------------------------------------------------------

    def register_target(self, target_id: str) -> Any:
        """Create the receive side for ``target_id``; returns the opaque
        target token ``send`` addresses it by."""
        raise NotImplementedError

    def send(self, target: Any, handoff: KVHandoff,
             timeout_s: float = 30.0) -> None:
        raise NotImplementedError

    def recv(self, target_id: str, timeout_s: float = 0.1) -> Optional[KVHandoff]:
        """Bounded receive; None when nothing arrived within the
        timeout (callers poll — a transfer plane must never park a
        decode loop forever)."""
        raise NotImplementedError

    def close(self) -> None:
        pass

    def stats(self) -> dict:
        return {
            "connector": self.name,
            "num_sent": self.num_sent,
            "num_received": self.num_received,
            "num_dropped": self.num_dropped,
            "bytes_sent": self.bytes_sent,
        }

    # -- shared plumbing ------------------------------------------------------

    def _chaos_gate(self, handoff: KVHandoff, target_label: str) -> KVHandoff:
        """The ``disagg.kv_transfer`` chaos hook, applied on every send."""
        if _chaos.ACTIVE is None:
            return handoff
        for _f in _chaos.fire(
            "disagg.kv_transfer",
            kinds=(_chaos.DROP_KV_TRANSFER, _chaos.CORRUPT_KV_TRANSFER,
                   _chaos.DELAY_RPC),
            request_id=handoff.request_id, connector=self.name,
            target=target_label,
        ):
            if _f.kind == _chaos.DROP_KV_TRANSFER:
                self.num_dropped += 1
                raise KVTransferError(
                    f"chaos: dropped KV transfer of {handoff.request_id!r} "
                    f"to {target_label}"
                )
            if _f.kind == _chaos.DELAY_RPC:
                time.sleep(_f.delay_s)
            if _f.kind == _chaos.CORRUPT_KV_TRANSFER:
                handoff = _corrupt_handoff(handoff)
        return handoff


# ---------------------------------------------------------------------------
# in-process backend
# ---------------------------------------------------------------------------

# process-global queues so serve replicas (in-process async actors) and a
# same-process orchestrator share one transfer plane; namespaced so two
# apps/tests never cross-deliver
_INPROC_LOCK = threading.Lock()
_INPROC_QUEUES: dict[tuple, "queue.Queue[KVHandoff]"] = {}


class InProcessConnector(KVConnector):
    name = "inproc"

    def __init__(self, namespace: str = "default"):
        super().__init__()
        self.namespace = namespace
        self._targets: set = set()

    def register_target(self, target_id: str) -> str:
        with _INPROC_LOCK:
            _INPROC_QUEUES.setdefault((self.namespace, target_id), queue.Queue())
        self._targets.add(target_id)
        return target_id

    def _queue(self, target_id: str) -> "queue.Queue[KVHandoff]":
        with _INPROC_LOCK:
            q = _INPROC_QUEUES.get((self.namespace, target_id))
        if q is None:
            raise KVTransferError(
                f"unknown KV target {target_id!r} in namespace "
                f"{self.namespace!r} (register_target first)"
            )
        return q

    def send(self, target: str, handoff: KVHandoff,
             timeout_s: float = 30.0) -> None:
        handoff = self._chaos_gate(handoff, target)
        self._queue(target).put(handoff)
        self.num_sent += 1
        self.bytes_sent += handoff.nbytes

    def recv(self, target_id: str, timeout_s: float = 0.1) -> Optional[KVHandoff]:
        try:
            h = self._queue(target_id).get(timeout=timeout_s)
        except queue.Empty:
            return None
        self.num_received += 1
        return h

    def close(self) -> None:
        with _INPROC_LOCK:
            for tid in self._targets:
                _INPROC_QUEUES.pop((self.namespace, tid), None)
        self._targets.clear()


# ---------------------------------------------------------------------------
# cluster-RPC backend
# ---------------------------------------------------------------------------


# envelope headroom per chunk frame: the pickled RPC tuple around the
# raw chunk bytes (method name, target/xfer ids, seq ints, crc, the
# uint32 length prefix). Measured envelopes are <300 bytes; 4 KiB keeps
# every chunk frame strictly under the connector's frame budget.
CHUNK_MARGIN = 4096


class RpcKVConnector(KVConnector):
    """KV transfer over cluster/rpc.py framing.

    One connector instance can play both sides: ``register_target``
    lazily starts a local RpcServer (one per connector, shared across
    targets) routing ``kv_put_chunk`` frames into per-target queues;
    ``send`` dials the peer's (host, port) through a ClientPool with the
    transfer timeout bounding each call.

    Large handoffs degrade to MORE FRAMES, never a hard failure: the
    pickled handoff is split into seq-numbered chunks sized to stay
    under ``max_frame_bytes`` (default: the protocol's MAX_FRAME — the
    r10 client-side guard that used to fail multi-frame-sized exports
    loudly), reassembled receiver-side and CRC-verified over the whole
    blob before unpickling. A torn multi-frame send (sender died
    mid-transfer) is garbage-collected after the transfer timeout and
    the orchestrator re-prefills exactly as for a lost single frame.
    """

    name = "rpc"

    def __init__(self, host: str = "127.0.0.1", timeout_s: float = 30.0,
                 max_frame_bytes: Optional[int] = None):
        super().__init__()
        from ray_tpu.cluster.rpc import MAX_FRAME, ClientPool

        self._host = host
        self._timeout = timeout_s
        self.max_frame_bytes = int(max_frame_bytes or MAX_FRAME)
        if self.max_frame_bytes <= CHUNK_MARGIN:
            raise ValueError(
                f"max_frame_bytes must exceed {CHUNK_MARGIN} "
                f"(envelope headroom), got {self.max_frame_bytes}"
            )
        self._pool = ClientPool(timeout=timeout_s)
        self._server = None
        self._queues: dict[str, "queue.Queue[KVHandoff]"] = {}
        # in-flight multi-frame reassembly: xfer_id -> {target, total,
        # parts: {seq: bytes}, crc, deadline}
        self._partial: dict[str, dict] = {}
        self._lock = threading.Lock()

    def _ensure_server(self):
        from ray_tpu.cluster.rpc import RpcServer

        with self._lock:
            if self._server is None:
                srv = RpcServer(host=self._host)
                srv.route("kv_put_chunk", self._on_kv_chunk)
                srv.start()
                self._server = srv
            # invariant: _server is only read under _lock; returning the
            # local binding keeps the read inside the critical section
            return self._server

    def _on_kv_chunk(self, payload, peer):
        """One seq-numbered chunk of a pickled handoff. The final chunk
        (all present) joins, CRC-verifies the blob, unpickles, and
        delivers; mid-transfer state is bounded by the deadline GC."""
        import pickle
        import zlib

        target_id = payload["target"]
        xfer = payload["xfer"]
        total = int(payload["total"])
        with self._lock:
            q = self._queues.get(target_id)
            if q is None:
                raise KVTransferError(f"no such KV target {target_id!r} here")
            now = time.time()
            # GC torn transfers whose sender gave up (re-prefilled):
            # partial chunk sets must not accumulate forever
            for xid in [x for x, rec in self._partial.items()
                        if rec["deadline"] < now]:
                del self._partial[xid]
            rec = self._partial.setdefault(xfer, {
                "target": target_id, "total": total, "parts": {},
                "crc": int(payload["crc"]),
            })
            # deadline refreshes on EVERY chunk: a live sender (each of
            # whose calls is individually bounded by ttl_s) can stream an
            # N-chunk transfer for N*ttl_s without being GC'd mid-flight;
            # only a sender that went silent past ttl_s — whose own call
            # timed out, so it already re-prefilled — loses the partial
            rec["deadline"] = now + float(payload.get("ttl_s", 60.0))
            rec["parts"][int(payload["seq"])] = payload["data"]
            done = len(rec["parts"]) == rec["total"]
            if done:
                del self._partial[xfer]
        if not done:
            return {"ok": True, "have": int(payload["seq"]) + 1}
        blob = b"".join(rec["parts"][i] for i in range(rec["total"]))
        if (zlib.crc32(blob) & 0xFFFFFFFF) != rec["crc"]:
            raise KVTransferError(
                f"reassembled KV transfer {xfer!r} failed blob CRC "
                f"({rec['total']} chunks) — torn in flight"
            )
        q.put(pickle.loads(blob))
        return {"ok": True, "delivered": True}

    def register_target(self, target_id: str) -> tuple:
        srv = self._ensure_server()
        with self._lock:
            self._queues.setdefault(target_id, queue.Queue())
        host, port = srv.address
        return (host, port, target_id)

    def send(self, target: tuple, handoff: KVHandoff,
             timeout_s: Optional[float] = None) -> None:
        import pickle
        import uuid
        import zlib

        from ray_tpu.cluster.rpc import RemoteError, RpcError

        host, port, target_id = target
        handoff = self._chaos_gate(handoff, f"{host}:{port}/{target_id}")
        timeout = timeout_s if timeout_s is not None else self._timeout
        blob = pickle.dumps(handoff, protocol=5)
        crc = zlib.crc32(blob) & 0xFFFFFFFF
        cap = self.max_frame_bytes - CHUNK_MARGIN
        chunks = [blob[i : i + cap] for i in range(0, len(blob), cap)] or [b""]
        xfer = f"{handoff.request_id}-{uuid.uuid4().hex[:8]}"
        # timeout bounds the WHOLE transfer, not each chunk: a peer
        # answering every chunk just under a per-call bound would
        # otherwise hold the sender (and the orchestrator's transfer
        # thread) for N*timeout with the re-prefill budget never
        # consulted
        deadline = time.monotonic() + timeout
        try:
            client = self._pool.get((host, port))
            for seq, data in enumerate(chunks):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise KVTransferError(
                        f"KV transfer of {handoff.request_id!r} to "
                        f"{host}:{port}/{target_id} exceeded {timeout}s "
                        f"after {seq}/{len(chunks)} chunks"
                    )
                client.call(
                    "kv_put_chunk",
                    {"target": target_id, "xfer": xfer, "seq": seq,
                     "total": len(chunks), "crc": crc, "data": data,
                     "ttl_s": timeout},
                    timeout=remaining,
                )
        except (RpcError, RemoteError) as e:
            # the frames may or may not have landed (the receiver GCs a
            # torn chunk set); the orchestrator's re-prefill path is
            # idempotent (delivery watermarks), so at-most-once here is
            # the right failure mode
            raise KVTransferError(
                f"KV transfer of {handoff.request_id!r} to "
                f"{host}:{port}/{target_id} failed "
                f"(chunk {len(chunks)} max): {e}"
            ) from e
        self.num_sent += 1
        self.bytes_sent += handoff.nbytes

    def recv(self, target_id: str, timeout_s: float = 0.1) -> Optional[KVHandoff]:
        with self._lock:
            q = self._queues.get(target_id)
        if q is None:
            raise KVTransferError(f"target {target_id!r} not registered here")
        try:
            h = q.get(timeout=timeout_s)
        except queue.Empty:
            return None
        self.num_received += 1
        return h

    def close(self) -> None:
        self._pool.close_all()
        with self._lock:
            srv, self._server = self._server, None
            self._queues.clear()
            self._partial.clear()
        if srv is not None:
            srv.stop()


def make_connector(kind: str, **kwargs) -> KVConnector:
    if kind in ("inproc", "in_process", "inprocess"):
        return InProcessConnector(**kwargs)
    if kind == "rpc":
        return RpcKVConnector(**kwargs)
    if kind == "device":
        # deferred import: ray_tpu.fabric builds ON this interface
        from ray_tpu.fabric.device_connector import DeviceKVConnector

        return DeviceKVConnector(**kwargs)
    raise ValueError(
        f"unknown KV connector {kind!r}; one of: inproc, rpc, device"
    )
