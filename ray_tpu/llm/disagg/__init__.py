"""ray_tpu.llm.disagg — disaggregated prefill/decode serving.

Prefill and decode run on separate engine pools; a request migrates
once, as a ``KVHandoff`` (KV pages + sampler/request state) over a
pluggable ``KVConnector`` (in-process for tests/CPU, cluster-RPC for
hosts, ICI/device-direct slots in later). The ``DisaggOrchestrator``
routes new requests to the prefill pool, picks decode replicas with
queue-depth + prefix-cache awareness, and re-prefills on any transfer
loss with delivered-token watermarks keeping completion ids idempotent.

Serving surfaces: ``LLMConfig(disagg=DisaggConfig(...))`` turns the
OpenAI app's LLMServer into a disaggregated deployment
(llm/openai_api.py); ``serve/disagg.py`` builds the multi-deployment
variant with pinned (KV-affinity) routing.
"""

from ray_tpu.llm.disagg.connector import (
    InProcessConnector,
    KVConnector,
    KVTransferError,
    RpcKVConnector,
    make_connector,
)
from ray_tpu.llm.disagg.handoff import KVHandoff
from ray_tpu.llm.disagg.orchestrator import DisaggConfig, DisaggOrchestrator

__all__ = [
    "DisaggConfig",
    "DisaggOrchestrator",
    "InProcessConnector",
    "KVConnector",
    "KVHandoff",
    "KVTransferError",
    "RpcKVConnector",
    "make_connector",
]
