"""Disaggregated prefill/decode orchestration over engine pools.

The structural problem this removes: a colocated engine time-slices
prefill and decode on one device — every admitted long prompt stalls
every decoding request's next token (the ROADMAP's prefill-roofline and
chunked-prefill-overlap items are both symptoms). Here prefill and
decode run on SEPARATE engine pools and a request migrates exactly once:

    submit -> [prefill pool] --KVHandoff over a KVConnector--> [decode pool]

 * prefill engines run admission + prefill + first-token sampling, then
   export the sequence (``LLMEngine.export_request``) — they never
   decode, so their queue holds only prefill work;
 * the orchestrator picks a decode replica per handoff with awareness of
   queue depth (primary) and prefix-cache state (``peek_prefix_tokens``
   + hit rate as tiebreaks), then ships the handoff through the
   connector;
 * decode engines import (``LLMEngine.import_handoff``, zero recompute:
   ``num_cached_tokens`` covers every transferred position) and run pure
   decode rounds.

Failure model (mirrors r09 serving hardening): a handoff that is
dropped, times out, or arrives corrupt (checksum) is RE-PREFILLED on
another prefill engine with the request id and delivered-token watermark
preserved — consumers see each output position exactly once, whatever
died in the middle. A prefill engine that dies mid-step has its
in-flight requests re-homed the same way. Every hop lands in the
``ray_tpu.obs`` flight recorder as an ``llm.kv_transfer`` span tiling
between the prefill span and the first decode round, so the e2e
span-coverage gate keeps holding.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
import uuid
from typing import Any, Optional

import numpy as np

from ray_tpu.llm.disagg.connector import (
    InProcessConnector,
    KVConnector,
    KVTransferError,
    make_connector,
)
from ray_tpu.llm.disagg.handoff import KVHandoff
from ray_tpu.llm.engine import EngineConfig, LLMEngine, RequestOutput
from ray_tpu.llm.sampling import SamplingParams
from ray_tpu.obs import context as trace_context
from ray_tpu.obs import recorder as trace_recorder
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.llm.disagg.orchestrator")


@dataclasses.dataclass
class DisaggConfig:
    """Pool shape + transfer plane for one disaggregated deployment."""

    engine: EngineConfig = dataclasses.field(default_factory=EngineConfig)
    num_prefill: int = 1
    num_decode: int = 1
    connector: str = "inproc"           # "inproc" | "rpc" | "device"
    transfer_timeout_s: float = 30.0
    # re-prefill budget per request across transfer losses / prefill
    # deaths; exceeding it fails the request loudly (crash loop, not a
    # transient)
    max_handoff_retries: int = 2
    # decode pick: queue depth first, prefix-cache awareness as tiebreak
    cache_aware_pick: bool = True
    # prefix-aware routing (r17): among engines whose queue depth is
    # within ``depth_slack`` of the least-loaded one, prefer the engine
    # already holding the longest TIER-DISCOUNTED prefix of the prompt
    # (an HBM hit outranks a host hit outranks an object-store hit
    # outranks a miss). When no engine holds anything — or the engines
    # have no tiered cache — the pick degrades to the existing
    # queue-depth/peek ladder unchanged.
    prefix_aware_routing: bool = True
    depth_slack: int = 4
    # fetch-cost routing (r18, llm/kvfetch): a replica holding NOTHING
    # scores fetch_weight x the best holder's tier-discounted score —
    # when the holder is loaded past depth_slack, the pick spreads to a
    # cold replica that PULLS the prefix over the fetch plane instead
    # of piling onto the hot engine (or recomputing cold). False keeps
    # the r17 route-to-owner behavior (the bench's A/B baseline).
    fetch_cost_routing: bool = True
    # multi-slice fabric topology (fabric.FabricTopology or its dict
    # wire form): which slice each pool is pinned to and which
    # pool-pairs share a device mesh. The orchestrator consults it per
    # (prefill -> decode) edge: device-direct where meshes are shared,
    # RPC elsewhere, device-fault => degrade that edge to RPC under the
    # re-prefill budget. None with connector="device" assumes one
    # shared slice (the single-host CI shape).
    fabric: Any = None

    def __post_init__(self):
        if isinstance(self.engine, dict):
            self.engine = EngineConfig(**self.engine)
        if self.num_prefill < 1 or self.num_decode < 1:
            raise ValueError("num_prefill and num_decode must be >= 1")


class _PoolEngine:
    """One engine + its lock + loop-thread bookkeeping."""

    def __init__(self, engine: LLMEngine, index: int):
        self.engine = engine
        self.index = index
        self.lock = threading.Lock()

    def depth(self) -> int:
        e = self.engine
        return len(e.waiting) + len(e.running)


class DisaggOrchestrator:
    """Prefill pool + decode pool + KV transfer plane; one per model."""

    def __init__(
        self,
        config: DisaggConfig,
        params: Any = None,
        seed: int = 0,
        model_tag: str = "disagg",
        connector: Optional[KVConnector] = None,
    ):
        self.config = config
        self.model_tag = model_tag
        if params is None:
            import jax

            from ray_tpu.models import llama

            params = llama.init_params(config.engine.model, jax.random.key(seed))
        self.params = params  # shared, immutable: one copy for every engine

        self._prefill = [
            _PoolEngine(LLMEngine(config.engine, params=params, seed=seed), i)
            for i in range(config.num_prefill)
        ]
        self._decode = [
            _PoolEngine(LLMEngine(config.engine, params=params, seed=seed), i)
            for i in range(config.num_decode)
        ]
        for p in self._prefill:
            p.engine.model_tag = f"{model_tag}-prefill{p.index}"
        for d in self._decode:
            d.engine.model_tag = f"{model_tag}-decode{d.index}"

        # -- fabric: topology + per-edge transport selection ------------------
        from ray_tpu.fabric.topology import FabricTopology

        # the EFFECTIVE primary plane: an injected connector instance
        # outranks config.connector (which may sit at its "inproc"
        # default) — the degenerate topology below must see the same
        # answer, or an injected device plane would silently route
        # every edge over the auto-built RPC fallback
        aliases = {"in_process": "inproc", "inprocess": "inproc"}
        if connector is not None:
            primary = connector.name
        else:
            primary = aliases.get(config.connector, config.connector)
        self._primary = primary

        topo = config.fabric
        if isinstance(topo, dict):
            topo = FabricTopology.from_dict(topo)
        if topo is None:
            # degenerate topology: a device-primary fabric with no map
            # assumes one shared slice (single-host CI / one ICI
            # domain); host-path primaries get distinct slices so the
            # map honestly says "no shared mesh"
            shared = primary == "device"
            topo = FabricTopology()
            topo.add_pool("prefill", "prefill", "slice0", config.num_prefill)
            topo.add_pool("decode", "decode",
                          "slice0" if shared else "slice1", config.num_decode)
        self.topology = topo
        self._prefill_pool = topo.pool_of_role("prefill") or "prefill"
        self._decode_pool = topo.pool_of_role("decode") or "decode"

        # unique namespace per orchestrator: two orchestrators with the
        # same model_tag in one process (num_replicas=2 of an
        # LLMConfig(disagg=...) deployment) must never steal each
        # other's handoffs off the process-global queues
        self._ns = f"{model_tag}-{uuid.uuid4().hex[:8]}"

        # -- kvfetch wiring (r18): every pool engine meets on one
        # per-orchestrator prefix index + fetch registry, so a pick
        # that spreads load to a COLD engine lets that engine pull the
        # prefix over the fetch plane instead of recomputing it
        self._fetch_enabled = False
        if config.engine.kvtier is not None:
            from ray_tpu.llm.kvfetch import (
                LocalFetchClient,
                get_local_fetch_registry,
            )
            from ray_tpu.llm.kvtier import get_local_index

            index = get_local_index(self._ns)
            registry = get_local_fetch_registry(self._ns)
            for pool, role in ((self._prefill, "prefill"),
                               (self._decode, "decode")):
                for pe in pool:
                    key = f"{role}{pe.index}"
                    pe.engine.kvtier.attach_index(index, engine_key=key)
                    registry.register(key, pe.engine.kvtier)
                    if pe.engine.kvfetch is not None:
                        pe.engine.kvfetch.attach(LocalFetchClient(registry))
            self._fetch_enabled = config.fetch_cost_routing
        if connector is not None:
            self.connectors: dict[str, KVConnector] = {primary: connector}
        else:
            self.connectors = {primary: self._build_connector(primary)}
        if primary == "device":
            # the RPC fallback plane stays warm: a faulted device edge
            # degrades to it instead of retrying a broken DMA path
            self.connectors.setdefault("rpc", self._build_connector("rpc"))
        # back-compat alias: stats()/tests address "the" connector
        self.connector = self.connectors[primary]

        # (prefill engine, decode engine) -> transport backend. Device
        # edges exist only when the primary plane is device-direct AND
        # the topology says the pools share a mesh; every edge degrades
        # independently on a device-transfer fault.
        if primary == "device":
            pool_edge = topo.edge_backend(self._prefill_pool, self._decode_pool)
            # a topology override may name a plane we haven't built yet
            # (e.g. an explicit "inproc" edge): build it, or every
            # transfer on that edge would KeyError at send time
            self.connectors.setdefault(
                pool_edge, self._build_connector(pool_edge)
            )
        else:
            pool_edge = primary
        self._edge_backend: dict[tuple, str] = {
            (p.index, d.index): pool_edge
            for p in self._prefill for d in self._decode
        }
        self.num_fallbacks = 0
        self.transfers_by_backend: dict[str, int] = {}

        self._targets: dict[str, list] = {}
        for name, conn in self.connectors.items():
            if name == "device":
                # endpoint = the decode engine's own KV-cache device, so
                # the transport's device_put IS the final hop
                self._targets[name] = [
                    conn.register_target(
                        f"{model_tag}-decode{i}",
                        device=d.engine.kv_cache_device(),
                    )
                    for i, d in enumerate(self._decode)
                ]
            else:
                self._targets[name] = [
                    conn.register_target(f"{model_tag}-decode{i}")
                    for i in range(config.num_decode)
                ]

        self._lock = threading.Lock()
        self._update_fabric_gauges()
        # orchestrator-minted request ids: every engine counts its own
        # "req-N", so two prefill engines would both mint "req-0" and the
        # second submit would orphan the first's output queue
        self._counter = itertools.count()
        self._queues: dict[str, queue.Queue] = {}
        # rid -> {"prompt_ids", "sp", "trace", "tokens" (delivered
        # watermark), "attempts", "key_data"}: enough to re-prefill
        # idempotently on any engine
        self._inflight: dict[str, dict] = {}
        self.num_transfers = 0
        self.num_reprefills = 0
        self.num_transfer_failures = 0
        self._stop = False
        self._wake = threading.Event()
        # handoffs cross to the sender thread: a slow/stalled transfer
        # (multi-MB KV frame, transfer_timeout_s bound) must not stall
        # the prefill loop's next step behind it
        self._transfer_q: "queue.Queue[KVHandoff]" = queue.Queue()
        self._threads: list[threading.Thread] = []
        t = threading.Thread(
            target=self._transfer_loop, name="disagg-transfer", daemon=True
        )
        t.start()
        self._threads.append(t)
        for p in self._prefill:
            t = threading.Thread(
                target=self._prefill_loop, args=(p,),
                name=f"disagg-prefill-{p.index}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        for d in self._decode:
            t = threading.Thread(
                target=self._decode_loop, args=(d,),
                name=f"disagg-decode-{d.index}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _build_connector(self, kind: str) -> KVConnector:
        if kind == "inproc":
            return InProcessConnector(namespace=self._ns)
        if kind == "device":
            from ray_tpu.fabric.device_connector import DeviceKVConnector

            return DeviceKVConnector(namespace=self._ns)
        return make_connector(kind)

    # -- public API -----------------------------------------------------------

    def submit(
        self,
        prompt_token_ids: list,
        sampling_params: Optional[SamplingParams] = None,
        request_id: Optional[str] = None,
        trace: Optional[trace_context.TraceContext] = None,
    ) -> tuple[str, queue.Queue]:
        """Enqueue one request on the least-loaded prefill engine;
        returns (request_id, output queue). The queue yields
        RequestOutput objects (watermarked: each output position exactly
        once), an exception on terminal failure, or None after abort."""
        sp = sampling_params or SamplingParams()
        trace = trace or trace_context.current()
        rid = request_id or f"dreq-{next(self._counter)}"
        pe = self._pick_prefill(list(prompt_token_ids))
        q: queue.Queue = queue.Queue()
        with pe.lock:
            pe.engine.add_request(
                list(prompt_token_ids), sp, request_id=rid, trace=trace
            )
            req_trace = pe.engine.requests[rid].trace
        with self._lock:
            self._queues[rid] = q
            self._inflight[rid] = {
                "prompt_ids": list(prompt_token_ids), "sp": sp,
                "trace": req_trace, "tokens": [], "attempts": 0,
            }
        self._wake.set()
        return rid, q

    def generate(
        self,
        prompts: list,
        sampling_params: "SamplingParams | list[SamplingParams] | None" = None,
        timeout_s: float = 300.0,
    ) -> list:
        """Blocking batch helper (tests/bench); output token lists in order."""
        if sampling_params is None or isinstance(sampling_params, SamplingParams):
            sampling_params = [sampling_params or SamplingParams()] * len(prompts)
        subs = [self.submit(p, sp) for p, sp in zip(prompts, sampling_params)]
        finals = []
        deadline = time.time() + timeout_s
        for rid, q in subs:
            toks = None
            while True:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(f"request {rid} did not finish in time")
                try:
                    out = q.get(timeout=remaining)
                except queue.Empty:
                    raise TimeoutError(
                        f"request {rid} did not finish within {timeout_s}s"
                    ) from None
                if isinstance(out, BaseException):
                    raise out
                if out is None:
                    break
                if out.finished:
                    toks = out.output_token_ids
                    break
            finals.append(toks)
        return finals

    def abort(self, request_id: str) -> None:
        """Abort wherever the request currently lives (waiting on a
        prefill engine, in flight as a handoff, or decoding)."""
        with self._lock:
            self._inflight.pop(request_id, None)
            q = self._queues.pop(request_id, None)
        for pool in (self._prefill, self._decode):
            for pe in pool:
                with pe.lock:
                    pe.engine.abort_request(request_id)
        if q is not None:
            q.put(None)

    def queue_depths(self) -> dict:
        return {
            "prefill": [p.depth() for p in self._prefill],
            "decode": [d.depth() for d in self._decode],
        }

    def has_unfinished(self) -> bool:
        with self._lock:
            return bool(self._inflight)

    def num_inflight(self) -> int:
        """Requests not yet finished ANYWHERE — queued, decoding, or in
        transit as a handoff (queue_depths misses that last state)."""
        with self._lock:
            return len(self._inflight)

    def stats(self) -> dict:
        hit = sum(p.engine.prefix_hit_tokens for p in self._prefill + self._decode)
        lookup = sum(
            p.engine.prefix_lookup_tokens for p in self._prefill + self._decode
        )
        xfer = self.connector.stats()
        if len(self.connectors) > 1:
            # totals span every plane; "connector" stays the primary
            snaps = [c.stats() for c in self.connectors.values()]
            for field in ("num_sent", "num_received", "num_dropped",
                          "bytes_sent"):
                xfer[field] = sum(s.get(field, 0) for s in snaps)
        with self._lock:
            fabric = {
                "edges": [
                    {"src": f"prefill{s}", "dst": f"decode{d}", "backend": b}
                    for (s, d), b in sorted(self._edge_backend.items())
                ],
                "backends": dict(self.transfers_by_backend),
                "fallbacks": self.num_fallbacks,
            }
        fabric["topology"] = self.topology.to_dict()
        return {
            "prefill": [p.engine.stats() for p in self._prefill],
            "decode": [d.engine.stats() for d in self._decode],
            "transfer": {
                **xfer,
                "kv_transfers": self.num_transfers,
                "reprefills": self.num_reprefills,
                "transfer_failures": self.num_transfer_failures,
            },
            "fabric": fabric,
            "prefix_cache": {
                "hit_tokens": hit,
                "lookup_tokens": lookup,
                "hit_rate": round(hit / lookup, 4) if lookup else 0.0,
            },
        }

    def shutdown(self) -> None:
        self._stop = True
        self._wake.set()
        for t in self._threads:
            t.join(timeout=5)
        for conn in self.connectors.values():
            conn.close()

    # -- delivery (watermarked, idempotent across re-prefills) ----------------

    def _deliver(self, out: RequestOutput) -> None:
        with self._lock:
            rec = self._inflight.get(out.request_id)
            q = self._queues.get(out.request_id)
            if rec is None:
                return
            new = list(out.output_token_ids[len(rec["tokens"]):])
            rec["tokens"].extend(new)
            if out.finished:
                self._inflight.pop(out.request_id, None)
                self._queues.pop(out.request_id, None)
        if q is not None and (new or out.finished):
            q.put(dataclasses.replace(out, new_token_ids=new))

    def _fail_request(self, rid: str, exc: BaseException) -> None:
        with self._lock:
            self._inflight.pop(rid, None)
            q = self._queues.pop(rid, None)
        if q is not None:
            q.put(exc)

    # -- prefill side ---------------------------------------------------------

    def _prefill_loop(self, pe: _PoolEngine) -> None:
        consec_failures = 0
        while not self._stop:
            with pe.lock:
                busy = pe.engine.has_unfinished()
            if not busy:
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            handoffs: list[KVHandoff] = []
            # device-resident export when any edge out of this engine is
            # device-direct (the pages then never stage through host RAM;
            # an RPC edge chosen later converts with to_host())
            with self._lock:
                export_dev = any(
                    self._edge_backend.get((pe.index, d.index)) == "device"
                    for d in self._decode
                )
            try:
                with pe.lock:
                    outputs = pe.engine.step()
                    # everything still RUNNING after a prefill-pool step
                    # was just admitted: export it before it ever decodes
                    for req in list(pe.engine.running):
                        h = pe.engine.export_request(
                            req.request_id, keep_on_device=export_dev
                        )
                        h.src_engine = pe.index
                        handoffs.append(h)
            except BaseException as e:  # noqa: BLE001 — re-home in-flight work
                if self._stop:
                    return
                consec_failures += 1
                # a deterministic crash (recover() not helping) must not
                # spin forever: after 3 straight failures drain EVERY
                # request off this engine through the bounded re-prefill
                # path, so each one either lands elsewhere or fails
                # loudly at the budget
                self._recover_prefill(pe, e,
                                      drain_all=consec_failures >= 3)
                continue
            consec_failures = 0
            for out in outputs:
                self._deliver(out)  # finished-at-prefill + first tokens (TTFT)
            for h in handoffs:
                self._transfer_q.put(h)

    def _transfer_loop(self) -> None:
        """Dedicated sender thread for the whole transfer plane."""
        while not self._stop:
            try:
                h = self._transfer_q.get(timeout=0.05)
            except queue.Empty:
                continue
            try:
                self._transfer(h)
            except BaseException:  # noqa: BLE001 — sender must survive
                logger.exception("transfer of %r failed unexpectedly",
                                 h.request_id)

    def _recover_prefill(self, pe: _PoolEngine, exc: BaseException,
                         drain_all: bool = False) -> None:
        """A prefill engine died mid-step: requeue its in-flight requests
        through the bounded ``_requeue`` path — on ANOTHER prefill engine
        when one exists (replica death before handoff must not strand
        work behind the corpse). Attempts count against
        ``max_handoff_retries``, so a persistent crash loop terminates
        with a typed failure instead of spinning. ``drain_all``
        additionally evacuates still-WAITING requests (engine-wide
        deterministic failures never admit them, so recover() alone
        would leave them stuck)."""
        logger.warning("prefill engine %d failed: %r; re-homing", pe.index, exc)
        try:
            with pe.lock:
                rids = pe.engine.recover()
                if drain_all:
                    rids = list(dict.fromkeys(rids + list(pe.engine.requests)))
                for rid in rids:
                    req = pe.engine.requests.pop(rid, None)
                    if req is not None and req in pe.engine.waiting:
                        pe.engine.waiting.remove(req)
        except BaseException:  # noqa: BLE001 — engine torn beyond recover
            logger.exception("prefill engine %d unrecoverable", pe.index)
            with pe.lock:
                rids = list(pe.engine.requests)
                for rid in rids:
                    try:
                        pe.engine.abort_request(rid)
                    except BaseException:  # noqa: BLE001
                        pe.engine.requests.pop(rid, None)
        exclude = pe.index if len(self._prefill) > 1 else None
        for rid in rids:
            self._requeue(rid, exclude_index=exclude,
                          reason=f"prefill_death:{type(exc).__name__}")

    # -- transfer + decode pick ----------------------------------------------

    def _prefix_discounted(self, pe: _PoolEngine, prompt_token_ids: list,
                           lora_id=None) -> float:
        """Tier-discounted prefix score of ``prompt`` on one engine
        (read-only probe across HBM + host + object tiers). Caller
        holds pe.lock."""
        try:
            return float(
                pe.engine.peek_prefix_tiered(prompt_token_ids,
                                             lora_id)["discounted"]
            )
        except ValueError:
            return 0.0  # adapter not loaded there

    def _fetch_weight(self) -> float:
        """The fetch-cost discount multiplier (0.0 = r17 route-to-owner:
        a replica holding nothing is never preferred). Requires the
        prefetch worker: routing a request to a cold engine that can
        never actually pull the prefix would just be a recompute."""
        kvt = self.config.engine.kvtier
        if not self._fetch_enabled or kvt is None or not kvt.prefetch:
            return 0.0
        return float(kvt.fetch_weight)

    def _pick_prefill(self, prompt_token_ids: list) -> "_PoolEngine":
        """Prefill pick: the engine already holding the longest
        tier-discounted prefix of this prompt, bounded by depth slack
        (cache affinity must not pile onto a hot engine); depth ladder
        when nobody holds anything — the prefix-blind behavior. With
        fetch-cost routing a cold engine scores fetch_weight x the
        best holder (it will PULL the prefix), so an overloaded holder
        spreads instead of monopolizing its prefix."""
        if len(self._prefill) == 1:
            return self._prefill[0]
        depths = {p.index: p.depth() for p in self._prefill}
        if self.config.prefix_aware_routing:
            floor = min(depths.values())
            fw = self._fetch_weight()
            discs = {}
            for p in self._prefill:
                # beyond-slack engines matter only as FETCH SOURCES —
                # without the discount, don't pay their lock + probe
                if (fw <= 0.0
                        and depths[p.index] > floor + self.config.depth_slack):
                    continue
                with p.lock:
                    discs[p.index] = self._prefix_discounted(
                        p, prompt_token_ids
                    )
            best_disc = max(discs.values(), default=0.0)
            best = None
            for p in self._prefill:
                if depths[p.index] > floor + self.config.depth_slack:
                    continue
                eff = max(discs.get(p.index, 0.0), fw * best_disc)
                if eff <= 0.0:
                    continue
                cand = (eff, -depths[p.index], -p.index)
                if best is None or cand > best[0]:
                    best = (cand, p)
            if best is not None:
                return best[1]
        return min(self._prefill, key=lambda p: depths[p.index])

    def _pick_decode(self, handoff: KVHandoff) -> int:
        """Prefix-aware decode pick: among replicas within depth slack
        of the least-loaded one, route to the replica already holding
        the longest TIER-DISCOUNTED prefix of this prompt (an HBM hit
        outranks a host hit outranks an object-store hit outranks a
        miss — resurrection beats recompute, residency beats both).
        When no replica holds anything the pick falls back to the
        existing ladder: queue depth first, HBM peek + overall hit rate
        as tiebreaks."""
        scores = []
        discounted = []
        for d in self._decode:
            with d.lock:
                depth = d.depth()
                peek = 0
                hit_rate = 0.0
                disc = 0.0
                if self.config.cache_aware_pick:
                    try:
                        peek = d.engine.peek_prefix_tokens(
                            handoff.prompt_token_ids, handoff.lora_id
                        )
                    except ValueError:
                        peek = 0  # adapter not loaded there
                    lk = d.engine.prefix_lookup_tokens
                    hit_rate = d.engine.prefix_hit_tokens / lk if lk else 0.0
                if self.config.prefix_aware_routing:
                    disc = self._prefix_discounted(
                        d, handoff.prompt_token_ids, handoff.lora_id
                    )
            scores.append((depth, -peek, -hit_rate, d.index))
            discounted.append((disc, depth, d.index))
        if self.config.prefix_aware_routing:
            floor = min(depth for _d, depth, _i in discounted)
            slack = self.config.depth_slack
            fw = self._fetch_weight()
            best_disc = max((disc for disc, _d, _i in discounted),
                            default=0.0)
            best = max(
                ((max(disc, fw * best_disc), -depth, -i)
                 for disc, depth, i in discounted
                 if depth <= floor + slack),
                default=None,
            )
            if best is not None and best[0] > 0.0:
                return -best[2]
        return min(scores)[-1]

    def _transfer(self, handoff: KVHandoff) -> None:
        idx = self._pick_decode(handoff)
        src = handoff.src_engine if handoff.src_engine is not None else 0
        with self._lock:
            backend = self._edge_backend.get((src, idx), self._primary)
        conn = self.connectors[backend]
        if backend != "device":
            # host-path edge (or a degraded device edge): the pickling
            # connectors need host ndarrays + CRC sealing
            handoff = handoff.to_host()
        try:
            conn.send(
                self._targets[backend][idx], handoff,
                timeout_s=self.config.transfer_timeout_s,
            )
            self.num_transfers += 1
            with self._lock:
                self.transfers_by_backend[backend] = (
                    self.transfers_by_backend.get(backend, 0) + 1
                )
        except KVTransferError as e:
            self._transfer_failed(handoff, e, backend=backend,
                                  edge=(src, idx))

    def _fallback_edge(self, edge: tuple, reason: str) -> None:
        """Degrade one faulted device edge to its RPC fallback (counted
        once per edge); subsequent transfers on it — including this
        request's budgeted re-prefill — ride the wire."""
        src, dst = edge
        with self._lock:
            if self._edge_backend.get((src, dst)) != "device":
                return
            self._edge_backend[(src, dst)] = "rpc"
            self.num_fallbacks += 1
            # pool-level topology state degrades only when NO engine
            # edge between the pools still rides the device plane —
            # otherwise topology.edges() would contradict the live
            # per-engine edge list (partial degradation is per-edge)
            pool_degraded = all(
                b != "device" for b in self._edge_backend.values()
            )
        if pool_degraded:
            self.topology.mark_fallback(self._prefill_pool,
                                        self._decode_pool, reason)
        logger.warning(
            "fabric edge prefill%d->decode%d degraded to rpc (%s)",
            src, dst, reason[:120],
        )
        try:
            from ray_tpu.fabric import metrics as fabric_metrics

            fabric_metrics.transfer_fallbacks_counter().inc(1, tags={
                "model": self.model_tag,
                "edge": f"prefill{src}->decode{dst}",
            })
        except Exception:  # noqa: BLE001 — observability never breaks serving
            pass
        self._update_fabric_gauges()

    def _update_fabric_gauges(self) -> None:
        try:
            from ray_tpu.fabric import metrics as fabric_metrics

            g = fabric_metrics.edges_active_gauge()
            with self._lock:
                counts: dict[str, int] = {}
                for b in self._edge_backend.values():
                    counts[b] = counts.get(b, 0) + 1
            for b in ("device", "rpc", "inproc"):
                if counts.get(b) or b == self._primary:
                    g.set(counts.get(b, 0),
                          tags={"model": self.model_tag, "backend": b})
        except Exception:  # noqa: BLE001
            pass

    def _transfer_failed(self, handoff: KVHandoff, exc: BaseException,
                         backend: Optional[str] = None,
                         edge: Optional[tuple] = None) -> None:
        self.num_transfer_failures += 1
        self._obs_transfer_event(handoff, error=str(exc), backend=backend)
        if backend == "device" and edge is not None:
            self._fallback_edge(edge, reason=f"{type(exc).__name__}: {exc}")
        with self._lock:
            rec = self._inflight.get(handoff.request_id)
            if rec is not None:
                # the sampler key rides the retry: the re-prefilled request
                # continues the exact stream the lost handoff carried
                rec["key_data"] = np.asarray(handoff.key_data)
        self._requeue(handoff.request_id, reason=f"transfer:{exc}")

    def _requeue(self, rid: str, exclude_index: Optional[int] = None,
                 reason: str = "") -> None:
        """Re-prefill a request whose handoff (or prefill engine) was
        lost. Bounded by max_handoff_retries; the delivered-token prefix
        is restored so re-admission recomputes prompt+outputs and the
        continuation extends exactly what consumers already saw."""
        with self._lock:
            rec = self._inflight.get(rid)
            if rec is None:
                return  # finished/failed concurrently
            rec["attempts"] += 1
            attempts = rec["attempts"]
        if attempts > self.config.max_handoff_retries:
            self._fail_request(rid, KVTransferError(
                f"request {rid!r}: handoff failed {attempts} times "
                f"(last: {reason}); budget exhausted"
            ))
            return
        self.num_reprefills += 1
        candidates = [p for p in self._prefill if p.index != exclude_index]
        pe = min(candidates or self._prefill, key=lambda p: p.depth())
        import jax
        import jax.numpy as jnp

        with pe.lock:
            pe.engine.add_request(
                rec["prompt_ids"], rec["sp"], request_id=rid,
                trace=rec["trace"],
            )
            req = pe.engine.requests[rid]
            req.output_token_ids = list(rec["tokens"])
            # a re-prefill re-matches blocks its first attempt just
            # sealed; count it as a recompute (like a preemption) so the
            # self-match doesn't inflate the hit rate the decode pick
            # and /v1/stats trust
            req.num_preemptions += 1
            if rec.get("key_data") is not None:
                # preserve the sampler stream across engines even for
                # unseeded requests (engines share a seed, but belt and
                # braces: the key rides the retry)
                req._key = jax.random.wrap_key_data(
                    jnp.asarray(rec["key_data"])
                )
        logger.warning(
            "re-prefilling %s on prefill engine %d (attempt %d: %s)",
            rid, pe.index, attempts, reason,
        )
        self._wake.set()

    # -- decode side ----------------------------------------------------------

    def _decode_loop(self, de: _PoolEngine) -> None:
        target_id = f"{self.model_tag}-decode{de.index}"
        # (handoff, deadline, backend) — the backend that delivered it
        pending: list[tuple] = []
        consec_failures = 0
        conns = list(self.connectors.items())
        while not self._stop:
            with de.lock:
                busy = de.engine.has_unfinished()
            # bounded receive across every live transfer plane: poll
            # fast while decoding, park briefly idle
            per_conn = (0.001 if (busy or pending) else 0.05) / len(conns)
            h, src_backend = None, None
            for name, conn in conns:
                h = conn.recv(target_id, timeout_s=max(per_conn, 0.001))
                if h is not None:
                    src_backend = name
                    break
            if h is not None:
                if not h.verify():
                    edge = ((h.src_engine, de.index)
                            if h.src_engine is not None else None)
                    self._transfer_failed(
                        h, KVTransferError(
                            f"handoff {h.request_id!r} failed checksum on "
                            f"{target_id} (corrupt in flight)"
                        ),
                        backend=src_backend, edge=edge,
                    )
                else:
                    pending.append(
                        (h, time.time() + self.config.transfer_timeout_s,
                         src_backend)
                    )
            if pending:
                pending = self._try_imports(de, pending)
            if busy:
                try:
                    with de.lock:
                        outputs = de.engine.step()
                except BaseException as e:  # noqa: BLE001
                    if self._stop:
                        return
                    consec_failures += 1
                    logger.warning(
                        "decode engine %d failed: %r; recovering (attempt %d)",
                        de.index, e, consec_failures,
                    )
                    # escalation ladder, bounded: recover -> recover with
                    # a KV/allocator rebuild -> evacuate every request
                    # through the re-prefill budget. A deterministic
                    # failure must terminate loudly, not spin hot with
                    # all its requests hung.
                    recovered = False
                    if consec_failures <= 2:
                        try:
                            with de.lock:
                                de.engine.recover(
                                    rebuild_kv=consec_failures == 2
                                )
                            recovered = True
                        except BaseException:  # noqa: BLE001
                            logger.exception(
                                "decode engine %d recover failed", de.index
                            )
                    if not recovered:
                        with de.lock:
                            rids = list(de.engine.requests)
                            for rid in rids:
                                try:
                                    de.engine.abort_request(rid)
                                except BaseException:  # noqa: BLE001
                                    de.engine.requests.pop(rid, None)
                        for rid in rids:
                            self._requeue(
                                rid,
                                reason=f"decode_death:{type(e).__name__}",
                            )
                        consec_failures = 0
                    continue
                consec_failures = 0
                for out in outputs:
                    self._deliver(out)

    def _try_imports(self, de: _PoolEngine,
                     pending: list) -> list:
        """Import received handoffs; a full cache retries until decode
        frees blocks, bounded by the transfer deadline (then the request
        re-prefills elsewhere instead of hanging)."""
        from ray_tpu.llm.kv_cache import NoFreeBlocksError

        still: list = []
        for h, deadline, backend in pending:
            with self._lock:
                live = h.request_id in self._inflight
            if not live:
                continue  # aborted/failed meanwhile
            t_import0 = time.time()
            try:
                with de.lock:
                    de.engine.import_handoff(h)
            except NoFreeBlocksError:
                if time.time() >= deadline:
                    self._transfer_failed(h, KVTransferError(
                        f"decode engine {de.index} had no KV room for "
                        f"{h.request_id!r} within the transfer deadline"
                    ), backend=backend)
                else:
                    still.append((h, deadline, backend))
                continue
            except BaseException as e:  # noqa: BLE001 — bad handoff state
                self._transfer_failed(h, e, backend=backend)
                continue
            self._obs_transfer_span(h, de.index, t_import0, time.time(),
                                    backend=backend)
        return still

    # -- observability --------------------------------------------------------

    def _obs_transfer_span(self, h: KVHandoff, decode_index: int,
                           t_import0: float, t_done: float,
                           backend: Optional[str] = None) -> None:
        """llm.kv_transfer span: prefill-span end -> import complete.
        Tiles between engine.prefill and the first decode round so the
        request's e2e span coverage survives disaggregation."""
        backend = backend or self._primary
        try:
            ctx = trace_context.TraceContext.from_dict(h.trace)
            trace_recorder.get_recorder().record(
                "llm.kv_transfer", min(h.t_export, t_done), t_done, ctx=ctx,
                attrs={
                    "request_id": h.request_id,
                    "backend": backend,
                    "decode_engine": decode_index,
                    "kv_tokens": h.num_kv_tokens,
                    "bytes": h.nbytes,
                    "import_ms": round((t_done - t_import0) * 1e3, 3),
                },
            )
            from ray_tpu.obs import slo

            slo.record_kv_transfer(
                self.model_tag, backend,
                seconds=max(0.0, t_done - h.t_export), nbytes=h.nbytes,
            )
        except Exception:  # noqa: BLE001 — tracing must not break serving
            pass

    def _obs_transfer_event(self, h: KVHandoff, error: str,
                            backend: Optional[str] = None) -> None:
        try:
            ctx = trace_context.TraceContext.from_dict(h.trace)
            now = time.time()
            trace_recorder.get_recorder().record(
                "llm.kv_transfer_failed", now, now, ctx=ctx,
                attrs={"request_id": h.request_id, "error": error[:200],
                       "backend": backend or self._primary},
                status="error",
            )
        except Exception:  # noqa: BLE001
            pass
