"""Disaggregated prefill/decode orchestration over engine pools.

The structural problem this removes: a colocated engine time-slices
prefill and decode on one device — every admitted long prompt stalls
every decoding request's next token (the ROADMAP's prefill-roofline and
chunked-prefill-overlap items are both symptoms). Here prefill and
decode run on SEPARATE engine pools and a request migrates exactly once:

    submit -> [prefill pool] --KVHandoff over a KVConnector--> [decode pool]

 * prefill engines run admission + prefill + first-token sampling, then
   export the sequence (``LLMEngine.export_request``) — they never
   decode, so their queue holds only prefill work;
 * the orchestrator picks a decode replica per handoff with awareness of
   queue depth (primary) and prefix-cache state (``peek_prefix_tokens``
   + hit rate as tiebreaks), then ships the handoff through the
   connector;
 * decode engines import (``LLMEngine.import_handoff``, zero recompute:
   ``num_cached_tokens`` covers every transferred position) and run pure
   decode rounds.

Failure model (mirrors r09 serving hardening): a handoff that is
dropped, times out, or arrives corrupt (checksum) is RE-PREFILLED on
another prefill engine with the request id and delivered-token watermark
preserved — consumers see each output position exactly once, whatever
died in the middle. A prefill engine that dies mid-step has its
in-flight requests re-homed the same way. Every hop lands in the
``ray_tpu.obs`` flight recorder as an ``llm.kv_transfer`` span tiling
between the prefill span and the first decode round, so the e2e
span-coverage gate keeps holding.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
import uuid
from typing import Any, Optional

from ray_tpu.llm.disagg.connector import (
    InProcessConnector,
    KVConnector,
    KVTransferError,
    make_connector,
)
from ray_tpu.llm.disagg.handoff import KVHandoff
from ray_tpu.llm.engine import EngineConfig, LLMEngine, RequestOutput
from ray_tpu.llm.sampling import SamplingParams
from ray_tpu.obs import context as trace_context
from ray_tpu.obs import recorder as trace_recorder
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.llm.disagg.orchestrator")


@dataclasses.dataclass
class DisaggConfig:
    """Pool shape + transfer plane for one disaggregated deployment."""

    engine: EngineConfig = dataclasses.field(default_factory=EngineConfig)
    num_prefill: int = 1
    num_decode: int = 1
    connector: str = "inproc"           # "inproc" | "rpc"
    transfer_timeout_s: float = 30.0
    # re-prefill budget per request across transfer losses / prefill
    # deaths; exceeding it fails the request loudly (crash loop, not a
    # transient)
    max_handoff_retries: int = 2
    # decode pick: queue depth first, prefix-cache awareness as tiebreak
    cache_aware_pick: bool = True

    def __post_init__(self):
        if isinstance(self.engine, dict):
            self.engine = EngineConfig(**self.engine)
        if self.num_prefill < 1 or self.num_decode < 1:
            raise ValueError("num_prefill and num_decode must be >= 1")


class _PoolEngine:
    """One engine + its lock + loop-thread bookkeeping."""

    def __init__(self, engine: LLMEngine, index: int):
        self.engine = engine
        self.index = index
        self.lock = threading.Lock()

    def depth(self) -> int:
        e = self.engine
        return len(e.waiting) + len(e.running)


class DisaggOrchestrator:
    """Prefill pool + decode pool + KV transfer plane; one per model."""

    def __init__(
        self,
        config: DisaggConfig,
        params: Any = None,
        seed: int = 0,
        model_tag: str = "disagg",
        connector: Optional[KVConnector] = None,
    ):
        self.config = config
        self.model_tag = model_tag
        if params is None:
            import jax

            from ray_tpu.models import llama

            params = llama.init_params(config.engine.model, jax.random.key(seed))
        self.params = params  # shared, immutable: one copy for every engine

        self._prefill = [
            _PoolEngine(LLMEngine(config.engine, params=params, seed=seed), i)
            for i in range(config.num_prefill)
        ]
        self._decode = [
            _PoolEngine(LLMEngine(config.engine, params=params, seed=seed), i)
            for i in range(config.num_decode)
        ]
        for p in self._prefill:
            p.engine.model_tag = f"{model_tag}-prefill{p.index}"
        for d in self._decode:
            d.engine.model_tag = f"{model_tag}-decode{d.index}"

        if connector is not None:
            self.connector = connector
        elif config.connector in ("inproc", "in_process", "inprocess"):
            # unique namespace per orchestrator: two orchestrators with
            # the same model_tag in one process (num_replicas=2 of an
            # LLMConfig(disagg=...) deployment) must never steal each
            # other's handoffs off the process-global queues
            self.connector = InProcessConnector(
                namespace=f"{model_tag}-{uuid.uuid4().hex[:8]}"
            )
        else:
            self.connector = make_connector(config.connector)
        self._targets = [
            self.connector.register_target(f"{model_tag}-decode{i}")
            for i in range(config.num_decode)
        ]

        self._lock = threading.Lock()
        # orchestrator-minted request ids: every engine counts its own
        # "req-N", so two prefill engines would both mint "req-0" and the
        # second submit would orphan the first's output queue
        self._counter = itertools.count()
        self._queues: dict[str, queue.Queue] = {}
        # rid -> {"prompt_ids", "sp", "trace", "tokens" (delivered
        # watermark), "attempts", "key_data"}: enough to re-prefill
        # idempotently on any engine
        self._inflight: dict[str, dict] = {}
        self.num_transfers = 0
        self.num_reprefills = 0
        self.num_transfer_failures = 0
        self._stop = False
        self._wake = threading.Event()
        # handoffs cross to the sender thread: a slow/stalled transfer
        # (multi-MB KV frame, transfer_timeout_s bound) must not stall
        # the prefill loop's next step behind it
        self._transfer_q: "queue.Queue[KVHandoff]" = queue.Queue()
        self._threads: list[threading.Thread] = []
        t = threading.Thread(
            target=self._transfer_loop, name="disagg-transfer", daemon=True
        )
        t.start()
        self._threads.append(t)
        for p in self._prefill:
            t = threading.Thread(
                target=self._prefill_loop, args=(p,),
                name=f"disagg-prefill-{p.index}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        for d in self._decode:
            t = threading.Thread(
                target=self._decode_loop, args=(d,),
                name=f"disagg-decode-{d.index}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    # -- public API -----------------------------------------------------------

    def submit(
        self,
        prompt_token_ids: list,
        sampling_params: Optional[SamplingParams] = None,
        request_id: Optional[str] = None,
        trace: Optional[trace_context.TraceContext] = None,
    ) -> tuple[str, queue.Queue]:
        """Enqueue one request on the least-loaded prefill engine;
        returns (request_id, output queue). The queue yields
        RequestOutput objects (watermarked: each output position exactly
        once), an exception on terminal failure, or None after abort."""
        sp = sampling_params or SamplingParams()
        trace = trace or trace_context.current()
        rid = request_id or f"dreq-{next(self._counter)}"
        pe = min(self._prefill, key=lambda p: p.depth())
        q: queue.Queue = queue.Queue()
        with pe.lock:
            pe.engine.add_request(
                list(prompt_token_ids), sp, request_id=rid, trace=trace
            )
            req_trace = pe.engine.requests[rid].trace
        with self._lock:
            self._queues[rid] = q
            self._inflight[rid] = {
                "prompt_ids": list(prompt_token_ids), "sp": sp,
                "trace": req_trace, "tokens": [], "attempts": 0,
            }
        self._wake.set()
        return rid, q

    def generate(
        self,
        prompts: list,
        sampling_params: "SamplingParams | list[SamplingParams] | None" = None,
        timeout_s: float = 300.0,
    ) -> list:
        """Blocking batch helper (tests/bench); output token lists in order."""
        if sampling_params is None or isinstance(sampling_params, SamplingParams):
            sampling_params = [sampling_params or SamplingParams()] * len(prompts)
        subs = [self.submit(p, sp) for p, sp in zip(prompts, sampling_params)]
        finals = []
        deadline = time.time() + timeout_s
        for rid, q in subs:
            toks = None
            while True:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise TimeoutError(f"request {rid} did not finish in time")
                try:
                    out = q.get(timeout=remaining)
                except queue.Empty:
                    raise TimeoutError(
                        f"request {rid} did not finish within {timeout_s}s"
                    ) from None
                if isinstance(out, BaseException):
                    raise out
                if out is None:
                    break
                if out.finished:
                    toks = out.output_token_ids
                    break
            finals.append(toks)
        return finals

    def abort(self, request_id: str) -> None:
        """Abort wherever the request currently lives (waiting on a
        prefill engine, in flight as a handoff, or decoding)."""
        with self._lock:
            self._inflight.pop(request_id, None)
            q = self._queues.pop(request_id, None)
        for pool in (self._prefill, self._decode):
            for pe in pool:
                with pe.lock:
                    pe.engine.abort_request(request_id)
        if q is not None:
            q.put(None)

    def queue_depths(self) -> dict:
        return {
            "prefill": [p.depth() for p in self._prefill],
            "decode": [d.depth() for d in self._decode],
        }

    def has_unfinished(self) -> bool:
        with self._lock:
            return bool(self._inflight)

    def num_inflight(self) -> int:
        """Requests not yet finished ANYWHERE — queued, decoding, or in
        transit as a handoff (queue_depths misses that last state)."""
        with self._lock:
            return len(self._inflight)

    def stats(self) -> dict:
        hit = sum(p.engine.prefix_hit_tokens for p in self._prefill + self._decode)
        lookup = sum(
            p.engine.prefix_lookup_tokens for p in self._prefill + self._decode
        )
        return {
            "prefill": [p.engine.stats() for p in self._prefill],
            "decode": [d.engine.stats() for d in self._decode],
            "transfer": {
                **self.connector.stats(),
                "kv_transfers": self.num_transfers,
                "reprefills": self.num_reprefills,
                "transfer_failures": self.num_transfer_failures,
            },
            "prefix_cache": {
                "hit_tokens": hit,
                "lookup_tokens": lookup,
                "hit_rate": round(hit / lookup, 4) if lookup else 0.0,
            },
        }

    def shutdown(self) -> None:
        self._stop = True
        self._wake.set()
        for t in self._threads:
            t.join(timeout=5)
        self.connector.close()

    # -- delivery (watermarked, idempotent across re-prefills) ----------------

    def _deliver(self, out: RequestOutput) -> None:
        with self._lock:
            rec = self._inflight.get(out.request_id)
            q = self._queues.get(out.request_id)
            if rec is None:
                return
            new = list(out.output_token_ids[len(rec["tokens"]):])
            rec["tokens"].extend(new)
            if out.finished:
                self._inflight.pop(out.request_id, None)
                self._queues.pop(out.request_id, None)
        if q is not None and (new or out.finished):
            q.put(dataclasses.replace(out, new_token_ids=new))

    def _fail_request(self, rid: str, exc: BaseException) -> None:
        with self._lock:
            self._inflight.pop(rid, None)
            q = self._queues.pop(rid, None)
        if q is not None:
            q.put(exc)

    # -- prefill side ---------------------------------------------------------

    def _prefill_loop(self, pe: _PoolEngine) -> None:
        consec_failures = 0
        while not self._stop:
            with pe.lock:
                busy = pe.engine.has_unfinished()
            if not busy:
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            handoffs: list[KVHandoff] = []
            try:
                with pe.lock:
                    outputs = pe.engine.step()
                    # everything still RUNNING after a prefill-pool step
                    # was just admitted: export it before it ever decodes
                    for req in list(pe.engine.running):
                        handoffs.append(pe.engine.export_request(req.request_id))
            except BaseException as e:  # noqa: BLE001 — re-home in-flight work
                if self._stop:
                    return
                consec_failures += 1
                # a deterministic crash (recover() not helping) must not
                # spin forever: after 3 straight failures drain EVERY
                # request off this engine through the bounded re-prefill
                # path, so each one either lands elsewhere or fails
                # loudly at the budget
                self._recover_prefill(pe, e,
                                      drain_all=consec_failures >= 3)
                continue
            consec_failures = 0
            for out in outputs:
                self._deliver(out)  # finished-at-prefill + first tokens (TTFT)
            for h in handoffs:
                self._transfer_q.put(h)

    def _transfer_loop(self) -> None:
        """Dedicated sender thread for the whole transfer plane."""
        while not self._stop:
            try:
                h = self._transfer_q.get(timeout=0.05)
            except queue.Empty:
                continue
            try:
                self._transfer(h)
            except BaseException:  # noqa: BLE001 — sender must survive
                logger.exception("transfer of %r failed unexpectedly",
                                 h.request_id)

    def _recover_prefill(self, pe: _PoolEngine, exc: BaseException,
                         drain_all: bool = False) -> None:
        """A prefill engine died mid-step: requeue its in-flight requests
        through the bounded ``_requeue`` path — on ANOTHER prefill engine
        when one exists (replica death before handoff must not strand
        work behind the corpse). Attempts count against
        ``max_handoff_retries``, so a persistent crash loop terminates
        with a typed failure instead of spinning. ``drain_all``
        additionally evacuates still-WAITING requests (engine-wide
        deterministic failures never admit them, so recover() alone
        would leave them stuck)."""
        logger.warning("prefill engine %d failed: %r; re-homing", pe.index, exc)
        try:
            with pe.lock:
                rids = pe.engine.recover()
                if drain_all:
                    rids = list(dict.fromkeys(rids + list(pe.engine.requests)))
                for rid in rids:
                    req = pe.engine.requests.pop(rid, None)
                    if req is not None and req in pe.engine.waiting:
                        pe.engine.waiting.remove(req)
        except BaseException:  # noqa: BLE001 — engine torn beyond recover
            logger.exception("prefill engine %d unrecoverable", pe.index)
            with pe.lock:
                rids = list(pe.engine.requests)
                for rid in rids:
                    try:
                        pe.engine.abort_request(rid)
                    except BaseException:  # noqa: BLE001
                        pe.engine.requests.pop(rid, None)
        exclude = pe.index if len(self._prefill) > 1 else None
        for rid in rids:
            self._requeue(rid, exclude_index=exclude,
                          reason=f"prefill_death:{type(exc).__name__}")

    # -- transfer + decode pick ----------------------------------------------

    def _pick_decode(self, handoff: KVHandoff) -> int:
        """Queue depth first; prefix-cache awareness (how many of this
        prompt's tokens the replica already holds sealed, then its
        overall hit rate) breaks ties — the replica most likely to serve
        the NEXT same-prefix prompt from cache keeps accumulating it."""
        scores = []
        for d in self._decode:
            with d.lock:
                depth = d.depth()
                peek = 0
                hit_rate = 0.0
                if self.config.cache_aware_pick:
                    try:
                        peek = d.engine.peek_prefix_tokens(
                            handoff.prompt_token_ids, handoff.lora_id
                        )
                    except ValueError:
                        peek = 0  # adapter not loaded there
                    lk = d.engine.prefix_lookup_tokens
                    hit_rate = d.engine.prefix_hit_tokens / lk if lk else 0.0
            scores.append((depth, -peek, -hit_rate, d.index))
        return min(scores)[-1]

    def _transfer(self, handoff: KVHandoff) -> None:
        idx = self._pick_decode(handoff)
        try:
            self.connector.send(
                self._targets[idx], handoff,
                timeout_s=self.config.transfer_timeout_s,
            )
            self.num_transfers += 1
        except KVTransferError as e:
            self._transfer_failed(handoff, e)

    def _transfer_failed(self, handoff: KVHandoff, exc: BaseException) -> None:
        self.num_transfer_failures += 1
        self._obs_transfer_event(handoff, error=str(exc))
        with self._lock:
            rec = self._inflight.get(handoff.request_id)
            if rec is not None:
                # the sampler key rides the retry: the re-prefilled request
                # continues the exact stream the lost handoff carried
                rec["key_data"] = handoff.key_data
        self._requeue(handoff.request_id, reason=f"transfer:{exc}")

    def _requeue(self, rid: str, exclude_index: Optional[int] = None,
                 reason: str = "") -> None:
        """Re-prefill a request whose handoff (or prefill engine) was
        lost. Bounded by max_handoff_retries; the delivered-token prefix
        is restored so re-admission recomputes prompt+outputs and the
        continuation extends exactly what consumers already saw."""
        with self._lock:
            rec = self._inflight.get(rid)
            if rec is None:
                return  # finished/failed concurrently
            rec["attempts"] += 1
            attempts = rec["attempts"]
        if attempts > self.config.max_handoff_retries:
            self._fail_request(rid, KVTransferError(
                f"request {rid!r}: handoff failed {attempts} times "
                f"(last: {reason}); budget exhausted"
            ))
            return
        self.num_reprefills += 1
        candidates = [p for p in self._prefill if p.index != exclude_index]
        pe = min(candidates or self._prefill, key=lambda p: p.depth())
        import jax
        import jax.numpy as jnp

        with pe.lock:
            pe.engine.add_request(
                rec["prompt_ids"], rec["sp"], request_id=rid,
                trace=rec["trace"],
            )
            req = pe.engine.requests[rid]
            req.output_token_ids = list(rec["tokens"])
            # a re-prefill re-matches blocks its first attempt just
            # sealed; count it as a recompute (like a preemption) so the
            # self-match doesn't inflate the hit rate the decode pick
            # and /v1/stats trust
            req.num_preemptions += 1
            if rec.get("key_data") is not None:
                # preserve the sampler stream across engines even for
                # unseeded requests (engines share a seed, but belt and
                # braces: the key rides the retry)
                req._key = jax.random.wrap_key_data(
                    jnp.asarray(rec["key_data"])
                )
        logger.warning(
            "re-prefilling %s on prefill engine %d (attempt %d: %s)",
            rid, pe.index, attempts, reason,
        )
        self._wake.set()

    # -- decode side ----------------------------------------------------------

    def _decode_loop(self, de: _PoolEngine) -> None:
        target_id = f"{self.model_tag}-decode{de.index}"
        pending: list[tuple[KVHandoff, float]] = []  # (handoff, deadline)
        consec_failures = 0
        while not self._stop:
            with de.lock:
                busy = de.engine.has_unfinished()
            # bounded receive: poll fast while decoding, park briefly idle
            h = self.connector.recv(
                target_id, timeout_s=0.001 if (busy or pending) else 0.05
            )
            if h is not None:
                if not h.verify():
                    self._transfer_failed(
                        h, KVTransferError(
                            f"handoff {h.request_id!r} failed checksum on "
                            f"{target_id} (corrupt in flight)"
                        ),
                    )
                else:
                    pending.append(
                        (h, time.time() + self.config.transfer_timeout_s)
                    )
            if pending:
                pending = self._try_imports(de, pending)
            if busy:
                try:
                    with de.lock:
                        outputs = de.engine.step()
                except BaseException as e:  # noqa: BLE001
                    if self._stop:
                        return
                    consec_failures += 1
                    logger.warning(
                        "decode engine %d failed: %r; recovering (attempt %d)",
                        de.index, e, consec_failures,
                    )
                    # escalation ladder, bounded: recover -> recover with
                    # a KV/allocator rebuild -> evacuate every request
                    # through the re-prefill budget. A deterministic
                    # failure must terminate loudly, not spin hot with
                    # all its requests hung.
                    recovered = False
                    if consec_failures <= 2:
                        try:
                            with de.lock:
                                de.engine.recover(
                                    rebuild_kv=consec_failures == 2
                                )
                            recovered = True
                        except BaseException:  # noqa: BLE001
                            logger.exception(
                                "decode engine %d recover failed", de.index
                            )
                    if not recovered:
                        with de.lock:
                            rids = list(de.engine.requests)
                            for rid in rids:
                                try:
                                    de.engine.abort_request(rid)
                                except BaseException:  # noqa: BLE001
                                    de.engine.requests.pop(rid, None)
                        for rid in rids:
                            self._requeue(
                                rid,
                                reason=f"decode_death:{type(e).__name__}",
                            )
                        consec_failures = 0
                    continue
                consec_failures = 0
                for out in outputs:
                    self._deliver(out)

    def _try_imports(self, de: _PoolEngine,
                     pending: list) -> list:
        """Import received handoffs; a full cache retries until decode
        frees blocks, bounded by the transfer deadline (then the request
        re-prefills elsewhere instead of hanging)."""
        from ray_tpu.llm.kv_cache import NoFreeBlocksError

        still: list = []
        for h, deadline in pending:
            with self._lock:
                live = h.request_id in self._inflight
            if not live:
                continue  # aborted/failed meanwhile
            t_import0 = time.time()
            try:
                with de.lock:
                    de.engine.import_handoff(h)
            except NoFreeBlocksError:
                if time.time() >= deadline:
                    self._transfer_failed(h, KVTransferError(
                        f"decode engine {de.index} had no KV room for "
                        f"{h.request_id!r} within the transfer deadline"
                    ))
                else:
                    still.append((h, deadline))
                continue
            except BaseException as e:  # noqa: BLE001 — bad handoff state
                self._transfer_failed(h, e)
                continue
            self._obs_transfer_span(h, de.index, t_import0, time.time())
        return still

    # -- observability --------------------------------------------------------

    def _obs_transfer_span(self, h: KVHandoff, decode_index: int,
                           t_import0: float, t_done: float) -> None:
        """llm.kv_transfer span: prefill-span end -> import complete.
        Tiles between engine.prefill and the first decode round so the
        request's e2e span coverage survives disaggregation."""
        try:
            ctx = trace_context.TraceContext.from_dict(h.trace)
            trace_recorder.get_recorder().record(
                "llm.kv_transfer", min(h.t_export, t_done), t_done, ctx=ctx,
                attrs={
                    "request_id": h.request_id,
                    "connector": self.connector.name,
                    "decode_engine": decode_index,
                    "kv_tokens": h.num_kv_tokens,
                    "bytes": h.nbytes,
                    "import_ms": round((t_done - t_import0) * 1e3, 3),
                },
            )
            from ray_tpu.obs import slo

            slo.record_kv_transfer(
                self.model_tag, self.connector.name,
                seconds=max(0.0, t_done - h.t_export), nbytes=h.nbytes,
            )
        except Exception:  # noqa: BLE001 — tracing must not break serving
            pass

    def _obs_transfer_event(self, h: KVHandoff, error: str) -> None:
        try:
            ctx = trace_context.TraceContext.from_dict(h.trace)
            now = time.time()
            trace_recorder.get_recorder().record(
                "llm.kv_transfer_failed", now, now, ctx=ctx,
                attrs={"request_id": h.request_id, "error": error[:200],
                       "connector": self.connector.name},
                status="error",
            )
        except Exception:  # noqa: BLE001
            pass
