"""KVHandoff: the unit a prefill engine exports and a decode engine imports.

One handoff = one request's complete migration state: the KV pages the
prefill pass wrote (slot-granular, position-ordered — connector backends
may repack but importers always receive [L, KVH, n_kv, D] position
order, the layout `SequenceBlocks.slots_for_range` maps straight back
onto any block assignment), plus everything the decode side needs to
continue the request *bit-identically*: sampler key state (raw
`jax.random.key_data`, so seeded and unseeded streams both survive the
hop), the sampled-so-far output prefix, logprob accounting, LoRA
identity, SLO timestamps, and the request's trace context.

Integrity: `seal()` stamps a CRC over the KV page bytes and the token
ids; `verify()` re-checks it on the receive side. A transfer plane that
bit-flips in flight (chaos: CORRUPT_KV_TRANSFER, or a real torn wire)
is detected at import time and handled as a lost transfer (re-prefill),
never silently decoded from garbage K/V.

Device path (ray_tpu.fabric): when the pages are device arrays riding
the ICI/device transport, `seal(device=True)` computes the page sum ON
the pages' device (`fabric.transport.device_checksum` — only a 4-byte
scalar crosses to the host) and records `checksum_kind="device_u32"`;
`verify()` dispatches on the kind, so the same handoff object flows
through either plane and a bit-flip is caught either way. `to_host()`
converts a device handoff back to host ndarrays + CRC sealing — the
orchestrator uses it when a device edge falls back to RPC.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Any, Optional

import numpy as np


@dataclasses.dataclass
class KVHandoff:
    request_id: str
    prompt_token_ids: list
    output_token_ids: list          # sampled so far (>=1: the prefill token)
    sampling_params: Any            # llm.sampling.SamplingParams
    key_data: np.ndarray            # jax.random.key_data of the request key
    num_kv_tokens: int              # positions covered by the pages below
    k_pages: np.ndarray             # [L, KVH, num_kv_tokens, D]
    v_pages: np.ndarray
    model_sig: tuple                # (n_layers, n_kv_heads, head_dim)
    lora_id: Optional[str] = None
    cumulative_logprob: float = 0.0
    token_logprobs: list = dataclasses.field(default_factory=list)
    # SLO timestamps ride the handoff so the decode engine's llm.request
    # root span / TTFT / e2e keep pricing the REQUEST, not the hop
    t_arrival: float = 0.0
    t_first_prefill: Optional[float] = None
    t_first_token: Optional[float] = None
    t_export: float = 0.0           # prefill-side export time (span start)
    trace: Optional[dict] = None    # TraceContext.to_dict wire form
    # which prefill engine exported this handoff (fabric edge
    # attribution: a corrupt arrival degrades exactly the faulted
    # (src -> dst) edge); advisory, not covered by the checksum
    src_engine: Optional[int] = None
    checksum: int = 0
    checksum_kind: str = "crc32"    # "crc32" (host) | "device_u32" (fabric)

    # -- integrity -----------------------------------------------------------

    def _token_crc(self) -> int:
        return zlib.crc32(
            np.asarray(self.prompt_token_ids + self.output_token_ids,
                       np.int64).tobytes()
        ) & 0xFFFFFFFF

    def _crc(self) -> int:
        crc = zlib.crc32(np.ascontiguousarray(self.k_pages).tobytes())
        crc = zlib.crc32(np.ascontiguousarray(self.v_pages).tobytes(), crc)
        crc = zlib.crc32(
            np.asarray(self.prompt_token_ids + self.output_token_ids,
                       np.int64).tobytes(),
            crc,
        )
        return crc & 0xFFFFFFFF

    def _device_sum(self) -> int:
        # page sums reduce on the pages' own device; token ids are a
        # tiny host list (CRC'd host-side) — the multi-MB payload never
        # crosses to the host for integrity. Delegates to the ONE
        # chained-fold implementation (ArrayBundle._sum: name-bound, so
        # K and V delivered swapped fail verify like the host CRC
        # would), then folds the token CRC on top.
        from ray_tpu.fabric.transport import ArrayBundle

        crc = ArrayBundle("", {"k_pages": self.k_pages,
                               "v_pages": self.v_pages})._sum()
        return zlib.crc32(self._token_crc().to_bytes(4, "big"), crc) & 0xFFFFFFFF

    def seal(self, device: bool = False) -> "KVHandoff":
        if device:
            self.checksum_kind = "device_u32"
            self.checksum = self._device_sum()
        else:
            self.checksum_kind = "crc32"
            self.checksum = self._crc()
        return self

    def verify(self) -> bool:
        if self.checksum_kind == "device_u32":
            return self.checksum == self._device_sum()
        return self.checksum == self._crc()

    def to_host(self) -> "KVHandoff":
        """Host-side copy (np pages, CRC-sealed): the form the pickling
        RPC/in-process connectors ship. A handoff already on the host is
        returned as-is."""
        if self.checksum_kind == "crc32" and isinstance(self.k_pages, np.ndarray):
            return self
        return dataclasses.replace(
            self,
            k_pages=np.asarray(self.k_pages),
            v_pages=np.asarray(self.v_pages),
        ).seal()

    @property
    def nbytes(self) -> int:
        return int(self.k_pages.nbytes + self.v_pages.nbytes)
