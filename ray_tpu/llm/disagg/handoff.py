"""KVHandoff: the unit a prefill engine exports and a decode engine imports.

One handoff = one request's complete migration state: the KV pages the
prefill pass wrote (slot-granular, position-ordered — connector backends
may repack but importers always receive [L, KVH, n_kv, D] position
order, the layout `SequenceBlocks.slots_for_range` maps straight back
onto any block assignment), plus everything the decode side needs to
continue the request *bit-identically*: sampler key state (raw
`jax.random.key_data`, so seeded and unseeded streams both survive the
hop), the sampled-so-far output prefix, logprob accounting, LoRA
identity, SLO timestamps, and the request's trace context.

Integrity: `seal()` stamps a CRC over the KV page bytes and the token
ids; `verify()` re-checks it on the receive side. A transfer plane that
bit-flips in flight (chaos: CORRUPT_KV_TRANSFER, or a real torn wire)
is detected at import time and handled as a lost transfer (re-prefill),
never silently decoded from garbage K/V.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Any, Optional

import numpy as np


@dataclasses.dataclass
class KVHandoff:
    request_id: str
    prompt_token_ids: list
    output_token_ids: list          # sampled so far (>=1: the prefill token)
    sampling_params: Any            # llm.sampling.SamplingParams
    key_data: np.ndarray            # jax.random.key_data of the request key
    num_kv_tokens: int              # positions covered by the pages below
    k_pages: np.ndarray             # [L, KVH, num_kv_tokens, D]
    v_pages: np.ndarray
    model_sig: tuple                # (n_layers, n_kv_heads, head_dim)
    lora_id: Optional[str] = None
    cumulative_logprob: float = 0.0
    token_logprobs: list = dataclasses.field(default_factory=list)
    # SLO timestamps ride the handoff so the decode engine's llm.request
    # root span / TTFT / e2e keep pricing the REQUEST, not the hop
    t_arrival: float = 0.0
    t_first_prefill: Optional[float] = None
    t_first_token: Optional[float] = None
    t_export: float = 0.0           # prefill-side export time (span start)
    trace: Optional[dict] = None    # TraceContext.to_dict wire form
    checksum: int = 0

    # -- integrity -----------------------------------------------------------

    def _crc(self) -> int:
        crc = zlib.crc32(np.ascontiguousarray(self.k_pages).tobytes())
        crc = zlib.crc32(np.ascontiguousarray(self.v_pages).tobytes(), crc)
        crc = zlib.crc32(
            np.asarray(self.prompt_token_ids + self.output_token_ids,
                       np.int64).tobytes(),
            crc,
        )
        return crc & 0xFFFFFFFF

    def seal(self) -> "KVHandoff":
        self.checksum = self._crc()
        return self

    def verify(self) -> bool:
        return self.checksum == self._crc()

    @property
    def nbytes(self) -> int:
        return int(self.k_pages.nbytes + self.v_pages.nbytes)
