"""Sampling: per-request params + one jitted batched sampler.

Reference analog: the OpenAI-style sampling knobs in
python/ray/llm/_internal/serve/configs/openai_api_models.py (vLLM does
the actual sampling). Here sampling is a single jitted program over the
decode batch — temperature, top-k, top-p, greedy — driven by per-row
parameter vectors so mixed batches need no recompile.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 1.0
    top_k: int = 0          # 0 = off
    top_p: float = 1.0      # 1.0 = off
    stop_token_ids: tuple = ()
    ignore_eos: bool = False
    seed: Optional[int] = None
    logprobs: bool = False

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0


@jax.jit
def sample_tokens(
    logits: jax.Array,        # [B, V] fp32
    temperatures: jax.Array,  # [B] (0 = greedy)
    top_ks: jax.Array,        # [B] int32 (0 = off)
    top_ps: jax.Array,        # [B] (1.0 = off)
    keys: jax.Array,          # [B] PRNG keys
) -> tuple[jax.Array, jax.Array]:
    """Returns (tokens [B], logprobs [B]). All knobs vectorized per row."""
    V = logits.shape[-1]

    def one(logit, temp, k, p, key):
        greedy_tok = jnp.argmax(logit)
        # temperature
        t = jnp.where(temp <= 0.0, 1.0, temp)
        scaled = logit / t
        # top-k: mask everything below the k-th largest
        sorted_desc = jnp.sort(scaled)[::-1]
        kth = sorted_desc[jnp.clip(k - 1, 0, V - 1)]
        scaled = jnp.where((k > 0) & (scaled < kth), -jnp.inf, scaled)
        # top-p (nucleus): smallest prefix of sorted probs with mass >= p
        probs_sorted = jax.nn.softmax(jnp.sort(scaled)[::-1])
        cum = jnp.cumsum(probs_sorted)
        # keep tokens whose prob >= the cutoff prob at the nucleus boundary
        idx = jnp.searchsorted(cum, p)
        cutoff = jax.nn.softmax(scaled)[jnp.argsort(scaled)[::-1][jnp.clip(idx, 0, V - 1)]]
        probs = jax.nn.softmax(scaled)
        scaled = jnp.where((p < 1.0) & (probs < cutoff), -jnp.inf, scaled)
        sampled = jax.random.categorical(key, scaled)
        tok = jnp.where(temp <= 0.0, greedy_tok, sampled)
        logprob = jax.nn.log_softmax(logit)[tok]
        return tok.astype(jnp.int32), logprob

    return jax.vmap(one)(logits, temperatures, top_ks, top_ps, keys)
