"""Sampling: per-request params + one jitted batched sampler.

Reference analog: the OpenAI-style sampling knobs in
python/ray/llm/_internal/serve/configs/openai_api_models.py (vLLM does
the actual sampling). Here sampling is a single jitted program over the
decode batch — temperature, top-k, top-p, greedy — driven by per-row
parameter vectors so mixed batches need no recompile.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 1.0
    top_k: int = 0          # 0 = off
    top_p: float = 1.0      # 1.0 = off
    stop_token_ids: tuple = ()
    ignore_eos: bool = False
    seed: Optional[int] = None
    logprobs: bool = False

    def __post_init__(self):
        # validate at admission, not inside the jitted sampler: a bad
        # knob must 400 the request, not poison a whole decode batch
        if self.max_tokens < 1:
            raise ValueError(f"max_tokens must be >= 1, got {self.max_tokens}")
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}"
            )
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0 (0 = off), got {self.top_k}")
        # top_p = 0 is accepted (OpenAI clients send it) and means the
        # smallest possible nucleus: the single most likely token
        if not (0.0 <= self.top_p <= 1.0):
            raise ValueError(
                f"top_p must be in [0, 1], got {self.top_p}"
            )

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0

    @property
    def needs_full_sort(self) -> bool:
        """top_k beyond the TOP_CAP fast path: the capped sampler would
        silently clamp it, so the batch must take the full-sort path."""
        return self.top_k > TOP_CAP


# top-k/top-p filtering is applied on the TOP_CAP largest logits only:
# a full [V] sort per row per decode step was ~30 ms of the ~37 ms
# device step time at V=32000/B=16 (round-5 profile) — three bitonic
# sorts of 32k on the VPU. lax.top_k(256) is ~100x less work; exact for
# top_k <= 256 and for any nucleus that fits in the top 256 tokens
# (beyond that the tail carries negligible mass at sane temperatures).
# Batches containing a request with top_k > TOP_CAP take mode
# "full_sort" (the engine derives it per batch): exact over the whole
# vocab at the old full-sort price, instead of silently clamping.
TOP_CAP = 256


@functools.partial(jax.jit, static_argnames=("mode",))
def sample_tokens(
    logits: jax.Array,        # [B, V] fp32
    temperatures: jax.Array,  # [B] (0 = greedy)
    top_ks: jax.Array,        # [B] int32 (0 = off)
    top_ps: jax.Array,        # [B] (1.0 = off)
    keys: jax.Array,          # [B] PRNG keys
    mode: str = "full",       # static: "greedy" | "categorical" | "full" | "full_sort"
    done: Optional[jax.Array] = None,  # [B] bool: finished-row mask
) -> tuple[jax.Array, jax.Array]:
    """Returns (tokens [B], logprobs [B]). All knobs vectorized per row.

    `mode` is a STATIC fast-path selector the engine derives from the
    batch (sort-free paths when nobody needs top-k/top-p):
      * greedy: every row has temperature 0 — argmax only;
      * categorical: temperature sampling, no top-k/top-p — gumbel-max
        via jax.random.categorical, no sort;
      * full: top-k/top-p filtering on the TOP_CAP largest logits;
      * full_sort: exact filtering over the whole vocab — required when
        any row's top_k exceeds TOP_CAP (the capped path would clamp
        it and truncate any nucleus wider than TOP_CAP).

    `done` (the pipelined decode loop's on-device stop mask): finished
    rows' logits come from trash-slot reads, so they are replaced with
    a constant one-hot BEFORE any softmax/sort (garbage stays out of
    the filtering numerics) and the row deterministically emits token 0
    with logprob 0 — the host discards it via per-row ``n_emitted``.
    The masking is where-based, so live rows' draws are bitwise
    untouched (a row's stream must not depend on batch-mates being
    finished)."""
    if done is not None:
        onehot = jnp.zeros_like(logits).at[:, 0].set(1.0)
        logits = jnp.where(done[:, None], onehot, logits)
    if mode == "greedy":
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logprob = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1), tok[:, None], axis=-1
        )[:, 0]
        return _mask_done(tok, logprob, done)

    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    t = jnp.where(temperatures <= 0.0, 1.0, temperatures)[:, None]
    scaled = logits / t

    if mode == "categorical":
        # per-ROW keys (seeded-request reproducibility) -> vmap; gumbel-max
        # inside categorical needs no sort
        sampled = jax.vmap(jax.random.categorical)(keys, scaled).astype(jnp.int32)
        tok = jnp.where(temperatures <= 0.0, greedy_tok, sampled)
    else:
        V = logits.shape[-1]
        cap = V if mode == "full_sort" else min(TOP_CAP, V)
        if cap == V:
            # full sort: argsort, NOT lax.top_k(V) — top_k's partial
            # selection is O(V*cap), quadratic when cap reaches V
            top_idx = jnp.flip(jnp.argsort(scaled, axis=-1), axis=-1)
            top_vals = jnp.take_along_axis(scaled, top_idx, axis=-1)
        else:
            top_vals, top_idx = jax.lax.top_k(scaled, cap)  # [B, cap] descending
        pos = jnp.arange(cap)[None, :]
        # top-k: keep positions < k (k = 0/off or > cap keeps all)
        k = jnp.where((top_ks <= 0) | (top_ks > cap), cap, top_ks)[:, None]
        vals = jnp.where(pos < k, top_vals, -jnp.inf)
        # top-p: smallest prefix of the (sorted) probs with mass >= p.
        # The explicit pos==0 term makes "first token always kept" hold
        # at top_p = 0 too (where cum - probs < 0 is false everywhere)
        probs = jax.nn.softmax(vals, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = ((cum - probs) < top_ps[:, None]) | (pos == 0)
        vals = jnp.where(keep, vals, -jnp.inf)
        choice = jax.vmap(jax.random.categorical)(keys, vals)  # [B] in [0, cap)
        filtered = jnp.take_along_axis(top_idx, choice[:, None], axis=-1)[:, 0]
        # rows with no filtering active sample over the FULL vocab with
        # the same draw the "categorical" mode makes — a seeded request's
        # stream must not depend on whether a batch-mate uses top-k/p.
        # Greedy rows short-circuit per row: their token is argmax no
        # matter the knobs, so they never take the filtered branch
        plain = jax.vmap(jax.random.categorical)(keys, scaled)
        needs = ((top_ks > 0) | (top_ps < 1.0)) & (temperatures > 0.0)
        sampled = jnp.where(needs, filtered, plain)
        tok = jnp.where(temperatures <= 0.0, greedy_tok, sampled.astype(jnp.int32))

    logprob = jnp.take_along_axis(
        jax.nn.log_softmax(logits, axis=-1), tok[:, None], axis=-1
    )[:, 0]
    return _mask_done(tok, logprob, done)


def _mask_done(tok, logprob, done):
    """Deterministic pad output for finished rows (pipelined stop
    masks): token 0, logprob 0. Live rows pass through untouched."""
    if done is None:
        return tok, logprob
    return (
        jnp.where(done, 0, tok).astype(jnp.int32),
        jnp.where(done, 0.0, logprob),
    )


def target_probs(
    logits: jax.Array,        # [B, V] fp32
    temperatures: jax.Array,  # [B] (<= 0 treated as 1.0; greedy is the
                              # caller's short-circuit, not a distribution)
    top_ks: jax.Array,        # [B] int32 (0 = off)
    top_ps: jax.Array,        # [B] (1.0 = off)
) -> jax.Array:
    """The normalized full-vocab distribution `sample_tokens` draws from,
    with temperature + top-k + top-p applied EXACTLY (descending sort
    over the whole vocab, no TOP_CAP approximation).

    This is the speculative-decoding acceptance sampler's view of the
    target: acceptance runs once per K drafted tokens instead of once
    per decode step, so the full-vocab sort it pays is already amortized
    ~K-fold vs the per-step sampler (which is why the per-step path gets
    the capped approximation and this one gets the exact filter).
    Filtering mirrors sample_tokens: top-k keeps the k most likely, then
    top-p keeps the smallest prefix of the surviving (sorted) probs with
    mass >= p, first token always kept."""
    V = logits.shape[-1]
    t = jnp.where(temperatures <= 0.0, 1.0, temperatures)[:, None]
    scaled = logits / t
    # full descending sort: argsort, NOT lax.top_k(V) — top_k's partial
    # selection is O(V*k), quadratic at k=V (measured ~50x slower here)
    idx = jnp.flip(jnp.argsort(scaled, axis=-1), axis=-1)  # [B, V] descending
    vals = jnp.take_along_axis(scaled, idx, axis=-1)
    pos = jnp.arange(V)[None, :]
    k = jnp.where((top_ks <= 0) | (top_ks > V), V, top_ks)[:, None]
    vals = jnp.where(pos < k, vals, -jnp.inf)
    probs = jax.nn.softmax(vals, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = ((cum - probs) < top_ps[:, None]) | (pos == 0)
    p_sorted = jax.nn.softmax(jnp.where(keep, vals, -jnp.inf), axis=-1)
    # scatter back to vocab order
    B = logits.shape[0]
    return jnp.zeros_like(scaled).at[jnp.arange(B)[:, None], idx].set(p_sorted)
