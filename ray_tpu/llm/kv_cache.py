"""Paged KV cache: host-side block allocator + device cache layout.

The reference's serving path gets paged attention from vLLM
(python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py);
here the block manager is native. Design follows the paged-attention
idea (and the TPU ragged-paged-attention lineage, see PAPERS.md):

 * device cache = two arrays per model: K and V, each HEAD-MAJOR
   [n_layers, n_kv_heads, num_blocks * block_size, head_dim] — flat
   "slot" addressing (slot = block_id * block_size + offset) so prefill
   scatter and decode gather are single-index ops; head-major because
   the Pallas decode kernel DMAs per-head pages and Mosaic needs the
   sliced slots dim sublane-aligned next to head_dim;
 * host-side BlockAllocator hands out blocks, refcounts them, and
   reuses full blocks across requests via content hashing (prefix
   caching — hash chains over block token contents).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    num_blocks: int = 256
    block_size: int = 16  # tokens per block
    n_layers: int = 2
    n_kv_heads: int = 2
    head_dim: int = 16
    dtype: Any = jnp.bfloat16

    @property
    def num_slots(self) -> int:
        return self.num_blocks * self.block_size


def init_kv_cache(cfg: KVCacheConfig) -> dict[str, jax.Array]:
    shape = (cfg.n_layers, cfg.n_kv_heads, cfg.num_slots, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


class NoFreeBlocksError(Exception):
    pass


class BlockAllocator:
    """Refcounted block allocator with prefix caching.

    Full blocks are immutable once written and keyed by
    hash((parent_hash, tuple(block_tokens))); a request's trailing
    partial block is always private. Freed blocks with a hash linger in
    a reuse pool (LRU) until evicted by allocation pressure — a cache
    hit resurrects them without recompute.
    """

    def __init__(self, num_blocks: int, block_size: int):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free: list[int] = list(range(num_blocks - 1, -1, -1))
        self._refcount: dict[int, int] = {}
        # content hash -> block_id for REUSABLE blocks (ref >= 0; 0 means
        # only the cache holds it)
        self._hash_to_block: dict[int, int] = {}
        self._block_hash: dict[int, int] = {}
        # content_hash -> root salt of its chain (the first block's
        # parent_hash IS the salt, so roots are derived incrementally at
        # seal time). Survives block eviction — it is chain metadata,
        # not residency — so a resurrected chain still resolves; cleared
        # only by a full drop. Lets drop_prefix_cache(salt=...) scope an
        # invalidation to exactly one adapter's chains (fleet canary /
        # LoRA slot reuse) instead of nuking every tenant's cache.
        self._hash_salt: dict[int, int] = {}
        # LRU order of zero-ref cached blocks (eviction candidates)
        self._zero_ref_lru: list[int] = []
        # tiered-cache hooks (llm/kvtier): seal_listener(block_id, hash,
        # parent_hash, tokens, n_prefix_tokens) fires when a full block
        # becomes canonical under its hash; evict_listener(block_id,
        # hash) fires just before a zero-ref cached block is reused
        # (the pages are still intact — the spill path's window);
        # drop_listener() fires on drop_prefix_cache (invalidation,
        # never a spill: the cached K/V itself went stale)
        self.seal_listener = None
        self.evict_listener = None
        self.drop_listener = None

    # -- stats ---------------------------------------------------------------

    @property
    def num_free(self) -> int:
        return len(self._free) + len(self._zero_ref_lru)

    def blocks_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)

    # -- core ops ------------------------------------------------------------

    def _pop_block(self) -> int:
        if self._free:
            return self._free.pop()
        if self._zero_ref_lru:
            victim = self._zero_ref_lru.pop(0)  # oldest cached block
            h = self._block_hash.pop(victim, None)
            if h is not None:
                self._hash_to_block.pop(h, None)
                if self.evict_listener is not None:
                    # spill window: the victim's pages are still intact
                    # (its new owner writes only after this allocation
                    # returns). A failed spill must never break the
                    # allocation it rode on.
                    try:
                        self.evict_listener(victim, h)
                    except Exception:  # noqa: BLE001
                        pass
            return victim
        raise NoFreeBlocksError("KV cache exhausted")

    def allocate(self, n: int) -> list[int]:
        """n fresh private blocks (no hash)."""
        if self.num_free < n:
            raise NoFreeBlocksError(
                f"need {n} KV blocks, only {self.num_free} free"
            )
        out = []
        for _ in range(n):
            b = self._pop_block()
            self._refcount[b] = 1
            out.append(b)
        return out

    def free(self, blocks: list[int]) -> None:
        for b in blocks:
            rc = self._refcount.get(b, 0) - 1
            if rc > 0:
                self._refcount[b] = rc
                continue
            self._refcount.pop(b, None)
            if b in self._block_hash:
                # keep contents around for prefix reuse until evicted
                self._zero_ref_lru.append(b)
            else:
                self._free.append(b)

    # -- prefix caching -------------------------------------------------------

    @staticmethod
    def chain_hash(parent_hash: int, block_tokens: tuple) -> int:
        return hash((parent_hash, block_tokens))

    def drop_prefix_cache(self, salt: Optional[int] = None) -> None:
        """Invalidate cached prefixes: zero-ref cached blocks return to
        the free list, live blocks lose their hashes (they stay private to
        their sequences). Needed when cached K/V may no longer match what
        a salt would recompute — e.g. a LoRA slot being reused by a new
        adapter.

        With ``salt`` the drop is SCOPED to chains rooted at that salt
        (one adapter's prefixes): other tenants' cached chains — and the
        deep-tier copies behind them — survive the swap."""
        if salt is None:
            for b in self._zero_ref_lru:
                self._block_hash.pop(b, None)
                self._free.append(b)
            self._zero_ref_lru.clear()
            self._hash_to_block.clear()
            self._block_hash.clear()
            self._hash_salt.clear()
        else:
            for h in [h for h, s in self._hash_salt.items() if s == salt]:
                self._hash_salt.pop(h, None)
                b = self._hash_to_block.pop(h, None)
                if b is None:
                    continue
                self._block_hash.pop(b, None)
                if b in self._zero_ref_lru:
                    self._zero_ref_lru.remove(b)
                    self._free.append(b)
        if self.drop_listener is not None:
            # cascade: deeper tiers (llm/kvtier) hold K/V computed with
            # the same now-stale weights/adapters — invalidation, not
            # spill, and it must reach every tier plus the prefix index
            try:
                self.drop_listener(salt)
            except Exception:  # noqa: BLE001
                pass

    def register_full_block(self, block_id: int, content_hash: int,
                            parent_hash: Optional[int] = None,
                            tokens: Optional[tuple] = None,
                            n_prefix_tokens: int = 0) -> None:
        """Mark a just-written full block reusable under its content hash.
        ``parent_hash``/``tokens``/``n_prefix_tokens`` carry the chain
        metadata the tiered cache's spill path needs (sealers that don't
        care pass nothing; the listener then never fires for them)."""
        existing = self._hash_to_block.get(content_hash)
        if existing is not None and existing != block_id:
            return  # another copy already canonical; keep ours private
        self._hash_to_block[content_hash] = block_id
        self._block_hash[block_id] = content_hash
        # root-salt derivation: a chain's first block has parent_hash ==
        # its salt, so the root propagates hash-to-hash with one lookup
        parent = parent_hash if parent_hash is not None else 0
        self._hash_salt[content_hash] = self._hash_salt.get(parent, parent)
        if self.seal_listener is not None and tokens is not None:
            try:
                self.seal_listener(block_id, content_hash,
                                   parent_hash if parent_hash is not None else 0,
                                   tokens, n_prefix_tokens)
            except Exception:  # noqa: BLE001 — bookkeeping, not correctness
                pass

    def contains_hash(self, content_hash: int) -> bool:
        """Read-only membership probe (no refs, no LRU motion) — the
        tiered probe walks per-block across HBM and the deep tiers."""
        return content_hash in self._hash_to_block

    def lookup(self, content_hash: int) -> Optional[int]:
        """Take a reference on a cached block if present."""
        b = self._hash_to_block.get(content_hash)
        if b is None:
            return None
        if b in self._zero_ref_lru:
            self._zero_ref_lru.remove(b)
        self._refcount[b] = self._refcount.get(b, 0) + 1
        return b

    def probe_prefix(self, tokens: list[int], salt: int = 0) -> int:
        """Tokens of `tokens` a prefix-cache hit WOULD cover — a
        read-only `match_prefix` that takes no references and moves no
        blocks. The disaggregated-serving decode pick uses it to score
        replicas by how much of a prompt's KV they already hold without
        perturbing LRU order or refcounts on the losers."""
        h = salt
        n_full = len(tokens) // self.block_size
        matched = 0
        for i in range(n_full):
            blk = tuple(tokens[i * self.block_size : (i + 1) * self.block_size])
            h = self.chain_hash(h, blk)
            if self._hash_to_block.get(h) is None:
                break
            matched += 1
        return matched * self.block_size

    def probe_admission_need(self, tokens: list[int], salt: int = 0) -> int:
        """Blocks a full prefill of ``tokens`` must take FROM THE FREE
        POOL, accounting for the prefix cache: a matched block that is
        LIVE-shared (refcount > 0) is adopted by refcount alone and
        costs nothing, while a matched zero-ref cached block still
        consumes a ``num_free`` slot when resurrected. Read-only (no
        refs taken, no LRU perturbation) — the engine's admission
        precheck uses it so a prefix-sharing request is never starved
        behind a free-pool check its cache hit would have satisfied."""
        need = self.blocks_needed(len(tokens))
        h = salt
        n_full = len(tokens) // self.block_size
        for i in range(n_full):
            blk = tuple(tokens[i * self.block_size : (i + 1) * self.block_size])
            h = self.chain_hash(h, blk)
            b = self._hash_to_block.get(h)
            if b is None:
                break
            if self._refcount.get(b, 0) > 0:
                need -= 1  # live shared: adoption is a refcount bump
        return need

    def match_prefix(self, tokens: list[int],
                     salt: int = 0) -> tuple[list[int], int, int]:
        """Longest cached chain of FULL blocks prefixing `tokens`.
        Returns (block_ids_with_refs_taken, num_tokens_matched, chain_hash).
        `salt` roots the chain (e.g. a LoRA adapter id): sequences under
        different adapters produce different K/V for the same tokens, so
        their prefixes must never cross-match."""
        matched: list[int] = []
        h = chain = salt
        n_full = len(tokens) // self.block_size
        for i in range(n_full):
            blk = tuple(tokens[i * self.block_size : (i + 1) * self.block_size])
            h = self.chain_hash(h, blk)
            b = self.lookup(h)
            if b is None:
                break
            matched.append(b)
            chain = h
        return matched, len(matched) * self.block_size, chain


@dataclasses.dataclass
class SequenceBlocks:
    """Per-request block bookkeeping (maps a token stream onto blocks)."""

    allocator: BlockAllocator
    blocks: list[int] = dataclasses.field(default_factory=list)
    num_tokens: int = 0
    # hash of the chain of sealed (hashed) full blocks (prefix-cache key)
    chain: int = 0
    num_sealed_tokens: int = 0  # tokens covered by sealed full blocks
    num_cached_tokens: int = 0  # prefix tokens reused from the cache

    def slot(self, pos: int) -> int:
        bs = self.allocator.block_size
        return self.blocks[pos // bs] * bs + pos % bs

    def slots_for_range(self, start: int, end: int) -> list[int]:
        return [self.slot(p) for p in range(start, end)]

    def ensure_capacity(self, num_tokens: int) -> None:
        need = self.allocator.blocks_needed(num_tokens) - len(self.blocks)
        if need > 0:
            self.blocks.extend(self.allocator.allocate(need))

    def seal_full_blocks(self, tokens: list[int]) -> None:
        """Register hashes for newly-completed full blocks. `tokens` is the
        COMPLETE token stream of the sequence so far."""
        bs = self.allocator.block_size
        n_full = len(tokens) // bs
        h = self.chain
        for i in range(self.num_sealed_tokens // bs, n_full):
            blk = tuple(tokens[i * bs : (i + 1) * bs])
            parent = h
            h = self.allocator.chain_hash(h, blk)
            self.allocator.register_full_block(
                self.blocks[i], h, parent_hash=parent, tokens=blk,
                n_prefix_tokens=(i + 1) * bs,
            )
        self.chain = h
        self.num_sealed_tokens = n_full * bs

    def truncate_to(self, num_tokens: int) -> int:
        """Roll the sequence back to ``num_tokens`` (speculative-decoding
        KV rollback: rejected draft positions sit in blocks past the
        accepted length). Whole blocks beyond the new length are freed;
        a freed block that carries a content hash stays resurrectable in
        the allocator's zero-ref pool, so the prefix cache is never
        corrupted — only over-reserved capacity is returned.

        Draft positions are never sealed (the engine seals accepted
        tokens only), so rolling back INTO the sealed prefix is a logic
        error: those blocks may be shared via the prefix cache and the
        chain hash cannot be recomputed without the token history.
        Returns the number of blocks freed."""
        if num_tokens < self.num_sealed_tokens:
            raise ValueError(
                f"cannot truncate to {num_tokens} tokens: {self.num_sealed_tokens} "
                "tokens are sealed into the prefix cache (rollback must stay "
                "past the accepted/sealed prefix)"
            )
        keep = self.allocator.blocks_needed(num_tokens) if num_tokens > 0 else 0
        dropped = self.blocks[keep:]
        if dropped:
            self.allocator.free(dropped)
            del self.blocks[keep:]
        self.num_tokens = num_tokens
        return len(dropped)

    def adopt_prefix(self, blocks: list[int], chain: int, num_tokens: int) -> None:
        """Start from a prefix-cache hit (refs already taken by match_prefix)."""
        self.blocks = list(blocks)
        self.chain = chain
        self.num_sealed_tokens = num_tokens
        self.num_cached_tokens = num_tokens

    def release(self) -> None:
        self.allocator.free(self.blocks)
        self.blocks = []
        self.num_tokens = 0
        self.chain = 0
        self.num_sealed_tokens = 0
        self.num_cached_tokens = 0
