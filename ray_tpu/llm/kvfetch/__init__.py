"""ray_tpu.llm.kvfetch — cross-engine KV resurrection, prefetch-at-
admission, and the async batched spill worker (r18).

r17's tiered cache (llm/kvtier) had three perf rungs left open, and
this package closes them:

 * **cross-engine resurrection** — a spilled ``SpilledBlock`` already
   IS a CRC-sealed ``KVHandoff``, so any same-weights replica can PULL
   it over the fetch plane (``plane.py``: in-process registry, fabric
   device transport, or a chunked ``kv_fetch`` RPC route) instead of
   the router having to pile every same-prefix request onto the one
   engine that spilled it. The prefix index's ``{engine, tier,
   n_tokens}`` rows (+ a published ``fetch_addr``) are the discovery
   surface; routing scores gain a ``fetch_weight`` discount so a cold
   replica that can fetch beats recomputing — but loses to any replica
   already holding the prefix locally.
 * **prefetch-at-admission** — ``manager.KVFetchManager`` verifies /
   deserializes / fetches a queued request's prefix on a bounded
   worker while the request waits, then scatters it into HBM (with
   reservation refs ``probe_admission_need`` discounts) on the engine
   thread BEFORE the request reaches the head of the queue;
   ``_prefill_one`` finds the blocks simply resident.
 * **async batched spill** — lives in ``kvtier/tiers.py``: eviction
   captures device slices only, a spill worker coalesces them into one
   batched device→host gather off the allocation hot path.

The bitwise-token-identity contract is unchanged on every new path:
each fetched or prefetched block re-verifies its seal + token ids
before a page is scattered; corrupt ⇒ counted drop + recompute, dead
source ⇒ bounded typed ``KVFetchError`` ⇒ recompute — never wrong
tokens, never a hang.
"""

from ray_tpu.llm.kvfetch.manager import KVFetchManager
from ray_tpu.llm.kvfetch.plane import (
    DeviceFetchClient,
    FetchClient,
    KVFetchError,
    LocalFetchClient,
    LocalFetchRegistry,
    RpcFetchClient,
    RpcFetchServer,
    get_local_fetch_registry,
    make_fetch_client,
)

__all__ = [
    "KVFetchManager",
    "KVFetchError",
    "FetchClient",
    "LocalFetchClient",
    "DeviceFetchClient",
    "RpcFetchClient",
    "RpcFetchServer",
    "LocalFetchRegistry",
    "get_local_fetch_registry",
    "make_fetch_client",
]
