"""KV fetch plane: cross-engine resurrection transport (ray_tpu.llm.kvfetch).

r17 left a spilled block resurrectable only on the engine that spilled
it — the router had to route TO that engine. Here a ``SpilledBlock``
(which already IS a CRC-sealed ``KVHandoff``: the r10 wire format) can
be PULLED by any same-weights replica over one of three backends, the
same ladder the r15 fabric gave the prefill→decode handoff path:

 * ``LocalFetchClient`` — direct registry call inside one process
   (serve replicas / a single orchestrator; the CI shape).
 * ``DeviceFetchClient`` — pages ride the fabric transfer plane
   (``fabric.transport.DeviceTransport``): the source's host-tier pages
   are moved to the requester's registered device endpoint exactly like
   a device-direct KV handoff (``jax.device_put`` — ICI DMA on a real
   pod, device memcpy on CPU CI); control rides the in-process registry.
 * ``RpcFetchClient`` / ``RpcFetchServer`` — the cross-host fallback:
   a ``kv_fetch`` route over ``cluster/rpc.py`` framing with the
   pickled block set split into seq-numbered ``kv_fetch_chunk`` pulls
   sized under MAX_FRAME (the r15 chunking discipline, pull-shaped).

Integrity is the requester's job in every backend: each fetched block
re-verifies its seal + token ids through ``KVTierManager``'s existing
``take_verified`` path before a single page is scattered — a corrupt
fetch is a counted drop + recompute, never wrong tokens. A dead or
stalled source is a BOUNDED typed ``KVFetchError`` (every call carries
a timeout), and the requester degrades to local-tiers-only.

Chaos: the source side of every backend passes the ``llm.kvfetch``
fire site (``serve_fetch`` in kvtier/tiers.py) with the existing
DROP/CORRUPT_KV_TRANSFER kinds.
"""

from __future__ import annotations

import pickle
import threading
import time
import uuid
import zlib
from typing import Any, Optional

import numpy as np

from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.llm.kvfetch")


class KVFetchError(Exception):
    """A cross-engine block fetch was dropped, timed out, or the source
    is gone. The requester's answer is always the same: serve what the
    LOCAL tiers hold and recompute the rest — never hang, never guess."""


# ---------------------------------------------------------------------------
# source registry (in-process control plane)
# ---------------------------------------------------------------------------

# process-global, namespaced like the in-process KV connector's queues:
# serve replicas and a same-process orchestrator meet on one registry,
# two apps never cross-resolve each other's engine keys
_REGISTRY_LOCK = threading.Lock()
_REGISTRIES: dict[str, "LocalFetchRegistry"] = {}


class LocalFetchRegistry:
    """engine_key -> fetch source (a ``KVTierManager``); the in-process
    face of the fetch plane's control side."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sources: dict[str, Any] = {}

    def register(self, engine_key: str, source: Any) -> None:
        with self._lock:
            self._sources[engine_key] = source

    def unregister(self, engine_key: str) -> None:
        with self._lock:
            self._sources.pop(engine_key, None)

    def get(self, engine_key: str) -> Any:
        with self._lock:
            src = self._sources.get(engine_key)
        if src is None:
            raise KVFetchError(
                f"no fetch source registered for engine {engine_key!r}"
            )
        return src

    def keys(self) -> list:
        with self._lock:
            return list(self._sources)


def get_local_fetch_registry(namespace: str) -> LocalFetchRegistry:
    with _REGISTRY_LOCK:
        reg = _REGISTRIES.get(namespace)
        if reg is None:
            reg = _REGISTRIES[namespace] = LocalFetchRegistry()
        return reg


# ---------------------------------------------------------------------------
# clients
# ---------------------------------------------------------------------------


class FetchClient:
    """Backend interface. ``fetch`` returns a list parallel to
    ``hashes``: a verified-shippable SpilledBlock per hash, or None for
    a hash the source no longer holds (the requester stops its chain
    walk there). Raises ``KVFetchError`` on transport-level loss —
    bounded by ``timeout_s`` in every backend."""

    name = "base"

    def __init__(self):
        self.num_fetches = 0
        self.num_blocks = 0
        self.num_failures = 0
        self.bytes_fetched = 0

    def fetch(self, engine_key: str, addr: Any, hashes: list,
              tokens_list: list, timeout_s: float = 5.0) -> list:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def stats(self) -> dict:
        return {
            "backend": self.name,
            "num_fetches": self.num_fetches,
            "num_blocks": self.num_blocks,
            "num_failures": self.num_failures,
            "bytes_fetched": self.bytes_fetched,
        }

    def _count(self, blocks: list) -> list:
        self.num_fetches += 1
        got = [b for b in blocks if b is not None]
        self.num_blocks += len(got)
        nbytes = sum(int(b.nbytes) for b in got)
        self.bytes_fetched += nbytes
        try:
            from ray_tpu.llm.kvfetch import metrics as kvfetch_metrics

            kvfetch_metrics.fetch_bytes_counter().inc(
                nbytes, tags={"backend": self.name}
            )
        except Exception:  # noqa: BLE001 — observability never breaks a fetch
            pass
        return blocks


class LocalFetchClient(FetchClient):
    """Direct in-process pull through the shared registry."""

    name = "local"

    def __init__(self, registry: LocalFetchRegistry):
        super().__init__()
        self.registry = registry

    def fetch(self, engine_key: str, addr: Any, hashes: list,
              tokens_list: list, timeout_s: float = 5.0) -> list:
        src = self.registry.get(engine_key)
        try:
            blocks = src.serve_fetch(hashes, tokens_list)
        except KVFetchError:
            self.num_failures += 1
            raise
        return self._count(blocks)


class DeviceFetchClient(FetchClient):
    """Pages ride the fabric transfer plane: the source's blocks are
    sent as one device-array bundle to THIS client's registered
    endpoint (``jax.device_put`` onto the endpoint's device — the ICI
    hop on a pod), then staged back to host ndarrays for the host-DRAM
    tier. Control (which blocks) rides the in-process registry — the
    same-process shape every fabric backend ships with on CI; a
    multi-host pod swaps the control hop for an RPC without touching
    this contract."""

    name = "device"

    def __init__(self, registry: LocalFetchRegistry, transport: Any = None,
                 endpoint_id: Optional[str] = None,
                 namespace: str = "kvfetch"):
        super().__init__()
        from ray_tpu.fabric.transport import DeviceTransport

        self.registry = registry
        self.transport = transport or DeviceTransport(namespace=namespace)
        self.endpoint_id = endpoint_id or f"kvfetch-{uuid.uuid4().hex[:8]}"
        self._target = self.transport.register_endpoint(self.endpoint_id)
        self._lock = threading.Lock()  # one in-flight fetch per client

    def fetch(self, engine_key: str, addr: Any, hashes: list,
              tokens_list: list, timeout_s: float = 5.0) -> list:
        import dataclasses as _dc

        from ray_tpu.fabric.transport import FabricTransferError

        src = self.registry.get(engine_key)
        xfer = uuid.uuid4().hex
        deadline = time.monotonic() + timeout_s
        with self._lock:
            try:
                blocks = src.serve_fetch(hashes, tokens_list)
            except KVFetchError:
                self.num_failures += 1
                raise
            arrays: dict = {}
            rows = []
            for i, sb in enumerate(blocks):
                if sb is None:
                    rows.append(None)
                    continue
                arrays[f"k{i}"] = sb.handoff.k_pages
                arrays[f"v{i}"] = sb.handoff.v_pages
                rows.append({
                    "i": i,
                    "header": _dc.replace(
                        sb.handoff,
                        k_pages=np.zeros((0,)), v_pages=np.zeros((0,)),
                    ),
                    "parent_hash": sb.parent_hash,
                    "n_prefix_tokens": sb.n_prefix_tokens,
                })
            try:
                self.transport.send_arrays(
                    self._target, arrays,
                    meta={"xfer": xfer, "rows": rows}, timeout_s=timeout_s,
                    bundle_id=f"kvfetch-{xfer[:8]}", seal=False,
                )
            except FabricTransferError as e:
                self.num_failures += 1
                raise KVFetchError(f"device fetch dropped: {e}") from e
            # drain until OUR bundle arrives: a stale bundle left by an
            # earlier timed-out fetch is discarded, never mistaken for
            # this transfer's payload (and never pins endpoint capacity)
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.num_failures += 1
                    raise KVFetchError(
                        f"device fetch from {engine_key!r} exceeded "
                        f"{timeout_s}s"
                    )
                b = self.transport.recv_arrays(
                    self.endpoint_id, timeout_s=max(0.001, remaining)
                )
                if b is None:
                    continue
                if b.meta.get("xfer") == xfer:
                    break
        out: list = [None] * len(hashes)
        for row in b.meta["rows"]:
            if row is None:
                continue
            i = row["i"]
            h = row["header"]
            # back to host ndarrays: the destination is the requester's
            # host-DRAM tier (the HBM scatter happens at consume time)
            h.k_pages = np.asarray(b.arrays[f"k{i}"])
            h.v_pages = np.asarray(b.arrays[f"v{i}"])
            from ray_tpu.llm.kvtier.tiers import SpilledBlock

            out[i] = SpilledBlock(
                handoff=h, parent_hash=row["parent_hash"],
                n_prefix_tokens=row["n_prefix_tokens"],
            )
        return self._count(out)

    def close(self) -> None:
        self.transport.close()


# ---------------------------------------------------------------------------
# RPC backend (cross-host fallback, chunked past MAX_FRAME)
# ---------------------------------------------------------------------------

# envelope headroom per chunk frame (mirrors the RpcKVConnector margin)
CHUNK_MARGIN = 4096


class RpcFetchServer:
    """One ``kv_fetch`` route serving every registered local source.

    ``kv_fetch`` prepares the pickled block set and returns the first
    chunk inline ({"xfer", "total", "crc", "data"}); the client pulls
    the rest with ``kv_fetch_chunk`` ({"xfer", "seq"}). Prepared blobs
    are GC'd past their deadline so a client that died mid-pull never
    strands server memory."""

    def __init__(self, host: str = "127.0.0.1",
                 max_frame_bytes: Optional[int] = None):
        from ray_tpu.cluster.rpc import MAX_FRAME

        # chunks sized well under the protocol ceiling: multi-MB block
        # sets degrade to MORE PULLS, never a frame-size failure
        self.max_frame_bytes = int(max_frame_bytes or min(MAX_FRAME, 8 << 20))
        if self.max_frame_bytes <= CHUNK_MARGIN:
            raise ValueError(
                f"max_frame_bytes must exceed {CHUNK_MARGIN}, "
                f"got {self.max_frame_bytes}"
            )
        self._host = host
        self._lock = threading.Lock()
        self._sources: dict[str, Any] = {}
        self._blobs: dict[str, dict] = {}  # xfer -> {chunks, deadline}
        self._server = None

    def register_source(self, engine_key: str, source: Any) -> tuple:
        """Register a KVTierManager under ``engine_key``; returns this
        server's (host, port) — the engine publishes it as its
        ``fetch_addr`` in the prefix index."""
        srv = self._ensure_server()
        with self._lock:
            self._sources[engine_key] = source
        return srv.address

    def _ensure_server(self):
        from ray_tpu.cluster.rpc import RpcServer

        with self._lock:
            if self._server is None:
                srv = RpcServer(host=self._host)
                srv.route("kv_fetch", self._on_fetch)
                srv.route("kv_fetch_chunk", self._on_chunk)
                srv.start()
                self._server = srv
            return self._server

    @property
    def address(self) -> tuple:
        return self._ensure_server().address

    def _on_fetch(self, payload, peer):
        engine_key = payload["engine"]
        with self._lock:
            src = self._sources.get(engine_key)
            now = time.time()
            for xid in [x for x, rec in self._blobs.items()
                        if rec["deadline"] < now]:
                del self._blobs[xid]
        if src is None:
            raise KVFetchError(f"no fetch source {engine_key!r} here")
        # serve_fetch applies the llm.kvfetch chaos gate (a DROP raises
        # out of this handler -> RemoteError -> typed KVFetchError at
        # the client) — called OUTSIDE the lock: it may materialize a
        # pending spill (a host copy) and must not stall other pulls
        blocks = src.serve_fetch(
            payload["hashes"], [tuple(t) for t in payload["tokens"]]
        )
        blob = pickle.dumps(blocks, protocol=5)
        crc = zlib.crc32(blob) & 0xFFFFFFFF
        cap = self.max_frame_bytes - CHUNK_MARGIN
        chunks = [blob[i: i + cap] for i in range(0, len(blob), cap)] or [b""]
        xfer = uuid.uuid4().hex
        if len(chunks) > 1:
            with self._lock:
                self._blobs[xfer] = {
                    "chunks": chunks,
                    "deadline": time.time() + float(payload.get("ttl_s", 60.0)),
                }
        return {"xfer": xfer, "total": len(chunks), "crc": crc,
                "data": chunks[0]}

    def _on_chunk(self, payload, peer):
        with self._lock:
            rec = self._blobs.get(payload["xfer"])
            if rec is None:
                raise KVFetchError(
                    f"fetch transfer {payload['xfer']!r} unknown or expired"
                )
            rec["deadline"] = time.time() + 60.0
            data = rec["chunks"][int(payload["seq"])]
            if int(payload["seq"]) == len(rec["chunks"]) - 1:
                del self._blobs[payload["xfer"]]
        return {"data": data}

    def stop(self) -> None:
        with self._lock:
            srv, self._server = self._server, None
            self._blobs.clear()
        if srv is not None:
            srv.stop()


class RpcFetchClient(FetchClient):
    """Pull blocks from a remote ``RpcFetchServer``: one ``kv_fetch``
    call + seq-numbered ``kv_fetch_chunk`` pulls, the WHOLE transfer
    bounded by one monotonic deadline (a peer answering each pull just
    under a per-call bound cannot hold the prefetch worker for
    N*timeout). A dead source is a typed, bounded ``KVFetchError``."""

    name = "rpc"

    def __init__(self, timeout_s: float = 5.0):
        super().__init__()
        from ray_tpu.cluster.rpc import ClientPool

        self._pool = ClientPool(timeout=timeout_s)

    def fetch(self, engine_key: str, addr: Any, hashes: list,
              tokens_list: list, timeout_s: float = 5.0) -> list:
        from ray_tpu.cluster.rpc import RemoteError, RpcError

        if not (isinstance(addr, (tuple, list)) and len(addr) == 2):
            self.num_failures += 1
            raise KVFetchError(
                f"engine {engine_key!r} published no usable fetch_addr "
                f"({addr!r})"
            )
        host, port = addr
        deadline = time.monotonic() + timeout_s
        try:
            client = self._pool.get((host, int(port)))
            got = client.call(
                "kv_fetch",
                {"engine": engine_key, "hashes": list(hashes),
                 "tokens": [list(t) for t in tokens_list],
                 "ttl_s": timeout_s},
                timeout=timeout_s,
            )
            parts = [got["data"]]
            for seq in range(1, got["total"]):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise KVFetchError(
                        f"fetch from {engine_key!r} exceeded {timeout_s}s "
                        f"after {seq}/{got['total']} chunks"
                    )
                parts.append(client.call(
                    "kv_fetch_chunk", {"xfer": got["xfer"], "seq": seq},
                    timeout=remaining,
                )["data"])
        except (RpcError, RemoteError, OSError) as e:
            self.num_failures += 1
            raise KVFetchError(
                f"fetch from {engine_key!r} at {host}:{port} failed: {e}"
            ) from e
        blob = b"".join(parts)
        if (zlib.crc32(blob) & 0xFFFFFFFF) != got["crc"]:
            self.num_failures += 1
            raise KVFetchError(
                f"fetch from {engine_key!r} failed blob CRC "
                f"({got['total']} chunks) — torn in flight"
            )
        return self._count(pickle.loads(blob))

    def close(self) -> None:
        self._pool.close_all()


def make_fetch_client(kind: str, **kwargs) -> FetchClient:
    if kind == "local":
        return LocalFetchClient(**kwargs)
    if kind == "device":
        return DeviceFetchClient(**kwargs)
    if kind == "rpc":
        return RpcFetchClient(**kwargs)
    raise ValueError(f"unknown fetch backend {kind!r}; one of: local, device, rpc")
