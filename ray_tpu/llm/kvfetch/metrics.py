"""kvfetch observability: prefetch/fetch/spill-queue series for the
``== kv tiers ==`` status block and /v1/stats.

Construct-per-call like obs/slo.py and kvtier/metrics.py (same-name
re-registration shares storage in util/metrics, so a test's
``clear_registry()`` can never strand a stale cached instance). All
series are telemetry-plane (``llm_`` is in
``obs.telemetry.AGGREGATED_PREFIXES``) and declare their aggregation
kinds, so ``check_metrics`` / ``check_aggregations`` hold them to the
same contract as every other cluster-rolled metric.
"""

from __future__ import annotations

_PREFETCH_PHASES = ("started", "completed", "wasted")


def prefetch_counter(phase: str):
    """One counter family per prefetch phase: started (task queued at
    admission), completed (consumed by the request's prefill), wasted
    (the request aborted/finished before its prefetch was consumed).
    Counters aggregate by SUM."""
    from ray_tpu.obs.telemetry import cluster_counter

    if phase not in _PREFETCH_PHASES:
        raise ValueError(f"unknown prefetch phase {phase!r}")
    return cluster_counter(
        f"llm_kvtier_prefetch_{phase}_total",
        description=f"KV prefix prefetches {phase} "
        "(prefetch-at-admission, ray_tpu.llm.kvfetch)",
        tag_keys=("model",),
    )


def fetch_bytes_counter():
    """Bytes of KV pages pulled from REMOTE engines over the fetch
    plane, labelled by transport backend like the r15 transfer metrics
    (local / device / rpc)."""
    from ray_tpu.obs.telemetry import cluster_counter

    return cluster_counter(
        "llm_kvtier_fetch_bytes_total",
        description="KV page bytes pulled from remote engines for "
        "cross-engine prefix resurrection, by fetch backend",
        tag_keys=("backend",),
    )


def fetch_corrupt_counter():
    """Fetched blocks that failed the requester-side seal/token verify
    — dropped and recomputed, never scattered."""
    from ray_tpu.obs.telemetry import cluster_counter

    return cluster_counter(
        "llm_kvtier_fetch_corrupt_dropped_total",
        description="remotely fetched KV blocks dropped because the "
        "requester-side verify failed (fell back to recompute)",
        tag_keys=("model",),
    )


def spill_queue_gauge():
    """Evicted blocks captured on-device awaiting the spill worker's
    batched gather. SUM across engines: the fleet's in-flight spill
    backlog."""
    from ray_tpu.obs.telemetry import cluster_gauge

    return cluster_gauge(
        "llm_kvtier_spill_queue_depth",
        description="evicted KV blocks queued for the async batched "
        "device->host spill gather",
        tag_keys=("model",),
    )


def prefetch_lead_histogram():
    """Seconds between a prefetch landing (blocks staged/resident) and
    the request's admission consuming it — how far ahead of the prefill
    the prefetch ran. Histograms aggregate by bucket merge."""
    from ray_tpu.obs.telemetry import cluster_histogram

    return cluster_histogram(
        "llm_kvtier_prefetch_lead_seconds",
        description="lead time between prefetch completion and the "
        "request's prefill admission consuming it",
        tag_keys=("model",),
        boundaries=[0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                    2.5, 5.0, 10.0],
    )


def register_metrics() -> None:
    """scripts/check_metrics.py hook: force lazy metrics to register."""
    for phase in _PREFETCH_PHASES:
        prefetch_counter(phase)
    fetch_bytes_counter()
    fetch_corrupt_counter()
    spill_queue_gauge()
    prefetch_lead_histogram()
