"""KVFetchManager: prefetch-at-admission + cross-engine pulls for one engine.

r17 resurrected spilled blocks SYNCHRONOUSLY inside ``_prefill_one`` —
the CRC re-verify, the object-store deserialize, and (were it remote)
the wire transfer all sat on the prefill admission path. Here that work
runs on a bounded prefetch worker while the request still waits in the
queue:

 1. ``request_admitted`` (engine thread, from ``add_request``) enqueues
    a prefetch task for the new request's prompt.
 2. The worker walks the prompt's chain hashes: blocks already resident
    in HBM are skipped; local host/object-tier entries are pulled and
    verified (``take_verified`` — the deserialize + CRC happen HERE,
    not at admission); blocks held by a REMOTE engine (prefix-index
    rows ``{engine, tier, n_tokens}`` + ``fetch_addr``) are pulled over
    the fetch plane (``llm/kvfetch/plane.py``), re-verified, and
    adopted into the local host tier. The verified chain is staged.
 3. ``tick`` (engine thread, from ``step()`` BEFORE admission) scatters
    each staged chain into the paged cache in ONE jitted set and
    registers the blocks with a RESERVATION ref — so by the time the
    request reaches the head of the queue, ``_prefill_one``'s
    ``match_prefix`` finds its prefix simply RESIDENT, and
    ``probe_admission_need`` already discounts the reserved blocks
    (they are live-shared).
 4. ``consumed`` (admission) releases the reservation; ``cancel``
    (abort/preempt) releases it too and drops staged state — an abort
    storm mid-prefetch leaks zero blocks and zero endpoint capacity.

Thread model: the worker touches ONLY thread-safe surfaces (the tier
manager under its lock, the fetch plane, the index) plus advisory
read-only peeks at allocator state; every allocator/cache MUTATION
(allocate/scatter/register/free) happens on the engine thread inside
``tick``/``consumed``/``cancel``, which the engine's owner already
serializes (the same contract as every other engine entry point).

Failure model: a dead/stalled fetch source is a BOUNDED typed
``KVFetchError`` — the request is served from local tiers + recompute;
a dark index (r13 STALL_GCS) means "no remote information" — local
tiers only; a corrupt fetched block fails the requester-side verify and
is a counted drop, never wrong tokens.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Optional

import numpy as np

from ray_tpu.llm.kvfetch.plane import FetchClient, KVFetchError
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.llm.kvfetch.manager")


class KVFetchManager:
    """Prefetch + cross-engine fetch orchestration for one LLMEngine."""

    def __init__(self, engine: Any):
        self.engine = engine
        self.cfg = engine.config.kvtier
        self.client: Optional[FetchClient] = None
        self._lock = threading.Lock()
        self._tasks: "queue.Queue[tuple]" = queue.Queue(
            maxsize=max(1, self.cfg.prefetch_queue_depth)
        )
        # rid -> {"entries": [(hash, SpilledBlock, n_prefix, src_tier)],
        #         "salt", "ready_t"} — verified chains awaiting the
        # engine-thread scatter
        self._staged: dict[str, dict] = {}
        # rid -> [block_ids]: the reservation ref held between the tick
        # scatter and admission (released on consume/cancel)
        self._reserved: dict[str, list] = {}
        # rid -> staged-ready time (feeds the prefetch-lead histogram)
        self._ready_t: dict[str, float] = {}
        # rid -> {source tier: tokens} for blocks the tick scattered:
        # they match as HBM residents at admission, and the engine's
        # per-tier hit accounting re-attributes them to the tier the
        # prefetch actually pulled them from
        self._attribution: dict[str, dict] = {}
        self._cancelled: dict[str, float] = {}
        self._busy = False  # worker mid-task (wait_idle visibility)
        # stats
        self.prefetch_started = 0
        self.prefetch_completed = 0
        self.prefetch_wasted = 0      # cancelled/finished before consumption
        self.prefetch_skipped = 0     # bounded task queue overflow
        self.prefetch_failures = 0    # worker task died (request unaffected)
        self.remote_fetches = 0
        self.remote_blocks = 0
        self.fetch_corrupt_dropped = 0
        self.fetch_failures = 0       # typed plane failures (drop/dead/timeout)
        self.index_dark = 0           # lookups answered by a dark index
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        if self.cfg.prefetch:
            t = threading.Thread(
                target=self._loop, name="kvfetch-prefetch", daemon=True
            )
            t.start()
            self._thread = t

    # -- wiring ---------------------------------------------------------------

    def attach(self, client: FetchClient) -> None:
        """Give this engine a fetch plane; without one, prefetch still
        runs (local-tiers verification/deserialize ahead of admission)
        but never pulls remote blocks."""
        self.client = client

    # -- engine-thread surface -------------------------------------------------

    def request_admitted(self, req: Any) -> None:
        """Called from add_request: queue a prefetch for this prompt
        (only when at least one full block could be covered)."""
        if not self.cfg.prefetch:
            return
        bs = self.engine.config.block_size
        if len(req.prompt_token_ids) <= bs:
            return
        try:
            self._tasks.put_nowait(
                (req.request_id, list(req.prompt_token_ids), req.lora_slot)
            )
            self.prefetch_started += 1
            try:
                from ray_tpu.llm.kvfetch import metrics as kvfetch_metrics

                kvfetch_metrics.prefetch_counter("started").inc(
                    1, tags={"model": self.engine.model_tag}
                )
            except Exception:  # noqa: BLE001
                pass
        except queue.Full:
            self.prefetch_skipped += 1

    def tick(self) -> None:
        """Engine thread, called from step() before admission: scatter
        every staged verified chain into the paged cache and hold a
        reservation ref per block. Bounded work: one jitted set per
        staged request, nothing when the stage is empty."""
        with self._lock:
            if not self._staged:
                return
            ready = list(self._staged.items())
            self._staged.clear()
        from ray_tpu.llm.engine import RequestStatus

        alloc = self.engine.allocator
        for rid, rec in ready:
            req = self.engine.requests.get(rid)
            with self._lock:
                cancelled = rid in self._cancelled
            if req is None or req.status != RequestStatus.WAITING or cancelled:
                self._note_wasted(rid)
                continue
            # drop entries that landed in HBM since staging (another
            # request shared the prefix) — the scatter must not duplicate
            entries = [e for e in rec["entries"]
                       if not alloc.contains_hash(e[0])]
            if not entries:
                with self._lock:
                    self._ready_t.setdefault(rid, rec["ready_t"])
                continue
            # starvation guard: prefetching a QUEUED request must not eat
            # the free blocks the head of the queue needs to admit — the
            # deep-tier copies stay resurrectable at admission instead
            head = self.engine.waiting[0] if self.engine.waiting else None
            if (head is not None and head.request_id != rid
                    and alloc.num_free - len(entries)
                    < self.engine._admission_need(head)):
                continue
            try:
                blocks = alloc.allocate(len(entries))
            except Exception:  # noqa: BLE001 — no room: resurrect at admission
                continue
            try:
                self._scatter(entries, blocks)
            except Exception:  # noqa: BLE001 — scatter died: release refs
                self.prefetch_failures += 1
                logger.exception("prefetch scatter for %s failed", rid)
                alloc.free(blocks)
                continue
            attr: dict = {}
            for _h, _sb, _npfx, src_tier in entries:
                t = src_tier.replace("remote:", "")
                attr[t] = attr.get(t, 0) + self.engine.config.block_size
            with self._lock:
                self._reserved[rid] = blocks
                self._ready_t.setdefault(rid, rec["ready_t"])
                self._attribution[rid] = attr

    def _scatter(self, entries: list, blocks: list) -> None:
        """One jitted KV-page set for a staged chain (the shared
        engine._scatter_block_pages recipe _resurrect_tiers also uses),
        then chain registration + tier promotion + resurrection
        accounting."""
        eng = self.engine
        bs = eng.config.block_size
        mgr = eng.kvtier
        k = np.concatenate([e[1].handoff.k_pages for e in entries], axis=2)
        v = np.concatenate([e[1].handoff.v_pages for e in entries], axis=2)
        eng._scatter_block_pages(k, v, blocks)
        tier_counts: dict[str, int] = {}
        for (h, sb, n_prefix, src_tier), b in zip(entries, blocks):
            eng.allocator.register_full_block(
                b, h, parent_hash=sb.parent_hash, tokens=sb.tokens,
                n_prefix_tokens=n_prefix,
            )
            # a block staged from a LOCAL tier is now promoted (drop the
            # deep copy); a REMOTE-fetched block was adopted by the
            # worker into whichever deep tier is enabled — promote that
            if src_tier.startswith("remote"):
                local = "host" if mgr.config.host_bytes > 0 else "object"
                mgr.promoted(h, local)
            else:
                mgr.promoted(h, src_tier)
            tier_counts[src_tier] = tier_counts.get(src_tier, 0) + bs
        for tier, n in tier_counts.items():
            mgr.count_resurrected(tier.replace("remote:", ""), n)

    def take_attribution(self, rid: str) -> dict:
        """{source tier: tokens} for blocks prefetch-scattered for this
        request — consumed once by _prefill_one's hit accounting so the
        per-tier mix reflects where the KV actually came from, not the
        HBM residency the prefetch manufactured."""
        with self._lock:
            return self._attribution.pop(rid, {})

    def consumed(self, rid: str) -> None:
        """Admission succeeded for ``rid``: its sequence holds its own
        refs now — release the reservation and book the lead time (how
        far ahead of admission the prefetch landed)."""
        with self._lock:
            blocks = self._reserved.pop(rid, None)
            ready_t = self._ready_t.pop(rid, None)
            self._cancelled.pop(rid, None)
            self._attribution.pop(rid, None)
        if blocks:
            self.engine.allocator.free(blocks)
        if ready_t is not None:
            self.prefetch_completed += 1
            try:
                from ray_tpu.llm.kvfetch import metrics as kvfetch_metrics

                tags = {"model": self.engine.model_tag}
                kvfetch_metrics.prefetch_counter("completed").inc(1, tags=tags)
                kvfetch_metrics.prefetch_lead_histogram().observe(
                    max(0.0, time.time() - ready_t), tags=tags
                )
            except Exception:  # noqa: BLE001
                pass

    def cancel(self, rid: str) -> None:
        """Abort/flush discipline: release the reservation refs AND the
        staged state for an aborted (or preempted-away) request — the
        regression contract is an abort storm mid-prefetch leaking zero
        blocks and zero endpoint capacity. Deep-tier/fetched copies stay
        in the bounded host LRU: they are cache, not a leak."""
        with self._lock:
            blocks = self._reserved.pop(rid, None)
            staged = self._staged.pop(rid, None)
            ready = self._ready_t.pop(rid, None)
            self._attribution.pop(rid, None)
            self._cancelled[rid] = time.time()
            # bounded tombstones: the worker consults them only to skip
            # a racing task, so pruning the oldest is always safe
            while len(self._cancelled) > 1024:
                self._cancelled.pop(next(iter(self._cancelled)))
        if blocks:
            self.engine.allocator.free(blocks)
        if blocks or staged or ready is not None:
            self._note_wasted(rid)

    def reset(self, forget_blocks: bool = False) -> None:
        """Crash-recovery flush (engine.recover): drop every staged
        chain and reservation. ``forget_blocks`` when the allocator was
        rebuilt — the old block ids died with it and must NOT be freed
        into the new one."""
        with self._lock:
            reserved, self._reserved = self._reserved, {}
            self._staged.clear()
            self._ready_t.clear()
            self._attribution.clear()
        if not forget_blocks:
            for blocks in reserved.values():
                try:
                    self.engine.allocator.free(blocks)
                except Exception:  # noqa: BLE001 — torn allocator state
                    pass

    def _note_wasted(self, rid: str) -> None:
        self.prefetch_wasted += 1
        try:
            from ray_tpu.llm.kvfetch import metrics as kvfetch_metrics

            kvfetch_metrics.prefetch_counter("wasted").inc(
                1, tags={"model": self.engine.model_tag}
            )
        except Exception:  # noqa: BLE001
            pass

    # -- worker ----------------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop:
            try:
                task = self._tasks.get(timeout=0.1)
            except queue.Empty:
                continue
            self._busy = True
            try:
                self._process(*task)
            except Exception:  # noqa: BLE001 — a failed prefetch is a no-op
                self.prefetch_failures += 1
                logger.exception("prefetch task failed")
            finally:
                self._busy = False

    def _process(self, rid: str, prompt: list, salt: int) -> None:
        from ray_tpu.llm.kv_cache import BlockAllocator

        with self._lock:
            if rid in self._cancelled:
                self._cancelled.pop(rid, None)
                self._note_wasted(rid)
                return
        eng = self.engine
        mgr = eng.kvtier
        # generation snapshot: a weight swap between here and staging
        # (or mid-fetch) invalidates everything this task produces
        gen0 = mgr.generation
        bs = eng.config.block_size
        # >=1 token stays un-cached so prefill yields next-token logits —
        # the same contract _resurrect_tiers keeps
        limit = (len(prompt) - 1) // bs
        h = salt
        plan: list = []  # (hash, block_tokens, n_prefix)
        for i in range(limit):
            blk = tuple(prompt[i * bs:(i + 1) * bs])
            h = BlockAllocator.chain_hash(h, blk)
            plan.append((h, blk, (i + 1) * bs))
        # classify: resident | local deep tier (verify NOW, off the
        # admission path) | needed from a remote holder
        entries: dict[int, tuple] = {}  # index -> staged entry
        needed: list = []               # (index, hash, blk, n_prefix)
        for i, (bh, blk, npfx) in enumerate(plan):
            if eng.allocator.contains_hash(bh):
                continue
            got = mgr.take_verified(bh, blk)
            if got is not None:
                entries[i] = (bh, got[1], npfx, got[0])
            else:
                needed.append((i, bh, blk, npfx))
        if needed and self.client is not None:
            self._fetch_remote(plan, needed, entries, gen0)
        # stage the longest CONTIGUOUS usable chain: every block index
        # must be resident or staged — a gap ends what admission can use
        staged: list = []
        for i, (bh, _blk, _npfx) in enumerate(plan):
            if eng.allocator.contains_hash(bh):
                continue
            e = entries.get(i)
            if e is None:
                break
            staged.append(e)
        with self._lock:
            if rid in self._cancelled or mgr.generation != gen0:
                # aborted, or a weight swap landed mid-task: the staged
                # chain references pre-swap KV — drop it entirely
                self._cancelled.pop(rid, None)
                self._note_wasted(rid)
                return
            self._staged[rid] = {
                "entries": staged, "salt": salt, "ready_t": time.time(),
            }
            if not staged:
                # nothing to scatter: the prefetch still "completed"
                # (local verification done / nothing coverable)
                self._staged.pop(rid, None)
                self._ready_t[rid] = time.time()
            # bounded bookkeeping: a ready mark landing AFTER its
            # request admitted is never consumed — pruning the oldest
            # is safe (the mark only feeds the lead-time histogram)
            while len(self._ready_t) > 4096:
                self._ready_t.pop(next(iter(self._ready_t)))

    def _fetch_remote(self, plan: list, needed: list,
                      entries: dict, gen0: int) -> None:
        """Pull the needed blocks from the best index-advertised remote
        holder; verified blocks are adopted into the LOCAL host tier
        (so a late prefetch still serves the admission-time resurrect)
        and staged for the tick scatter."""
        mgr = self.engine.kvtier
        index = mgr.index
        if index is None:
            return
        try:
            lookup = index.lookup([p[0] for p in plan])
        except Exception:  # noqa: BLE001 — dark index = no information
            lookup = None
        if not lookup:
            self.index_dark += 1
            return
        rows = lookup.get("engines") or {}
        best = None
        for key, row in rows.items():
            if key == mgr.engine_key:
                continue
            if row.get("age_s", 0.0) > self.cfg.index_stale_after_s:
                continue
            score = self.cfg.weight(row.get("tier")) * float(
                row.get("n_tokens", 0)
            )
            if score > 0.0 and (best is None or score > best[0]):
                best = (score, key, row)
        if best is None:
            return
        _score, src_key, row = best
        want = needed[: self.cfg.fetch_max_blocks]
        try:
            blocks = self.client.fetch(
                src_key, row.get("fetch_addr"),
                [w[1] for w in want], [w[2] for w in want],
                timeout_s=self.cfg.fetch_timeout_s,
            )
        except KVFetchError as e:
            self.fetch_failures += 1
            logger.warning("kvfetch from %s failed (%s); serving local "
                           "tiers only", src_key, e)
            return
        self.remote_fetches += 1
        for (i, bh, blk, npfx), sb in zip(want, blocks):
            if sb is None:
                continue
            if not mgr.verify_block(sb, blk):
                # corrupt in flight: counted drop, never scattered —
                # the chain breaks here and admission recomputes on
                self.fetch_corrupt_dropped += 1
                try:
                    from ray_tpu.llm.kvfetch import metrics as kvfetch_metrics

                    kvfetch_metrics.fetch_corrupt_counter().inc(
                        1, tags={"model": self.engine.model_tag}
                    )
                except Exception:  # noqa: BLE001
                    pass
                continue
            self.remote_blocks += 1
            # adopt into the local host tier: even if the tick scatter
            # never runs (cache pressure), admission resurrects locally
            # (gen-guarded: a swap mid-fetch drops the stale adoption)
            mgr.adopt_fetched(bh, sb, gen=gen0)
            entries[i] = (bh, sb, npfx, f"remote:{row.get('tier', 'host')}")

    # -- lifecycle / introspection --------------------------------------------

    def wait_idle(self, timeout_s: float = 10.0) -> bool:
        """Bounded wait until the task queue drains and the worker is
        between tasks (tests/bench determinism)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._tasks.empty() and not self._busy:
                return True
            time.sleep(0.002)
        return False

    def close(self) -> None:
        self._stop = True
        if self._thread is not None:
            self._thread.join(timeout=2)
        if self.client is not None:
            self.client.close()

    def stats(self) -> dict:
        with self._lock:
            staged = len(self._staged)
            reserved = sum(len(b) for b in self._reserved.values())
        out = {
            "prefetch": {
                "started": self.prefetch_started,
                "completed": self.prefetch_completed,
                "wasted": self.prefetch_wasted,
                "skipped": self.prefetch_skipped,
                "failures": self.prefetch_failures,
                "staged": staged,
                "reserved_blocks": reserved,
            },
            "remote": {
                "fetches": self.remote_fetches,
                "blocks": self.remote_blocks,
                "corrupt_dropped": self.fetch_corrupt_dropped,
                "failures": self.fetch_failures,
                "index_dark": self.index_dark,
            },
        }
        if self.client is not None:
            out["client"] = self.client.stats()
        return out
