"""Continuous-batching LLM engine.

The reference's engine is vLLM behind a Ray actor
(python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py);
this one is native and TPU-shaped:

 * static-shape buckets everywhere (prefill lengths, decode batch
   sizes) so XLA compiles a handful of programs once and the MXU sees
   fixed tiles — the TPU analog of CUDA-graph capture;
 * paged KV cache (llm/kv_cache.py) with prefix reuse;
 * scheduler: admit-prefill-then-decode with preemption by recompute,
   the vLLM v0 policy shape, host-side and O(batch);
 * sampling as one jitted vectorized program (llm/sampling.py).

Engine API mirrors vLLM's LLMEngine (add_request / step / generate) so
the serving layer (llm/openai_api.py) and batch processor sit on top
unchanged.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.chaos import harness as _chaos
from ray_tpu.llm.kv_cache import (
    BlockAllocator,
    NoFreeBlocksError,
    SequenceBlocks,
)
from ray_tpu.llm.sampling import SamplingParams, sample_tokens
from ray_tpu.models import llama
from ray_tpu.models.llama_decode import decode_step, init_cache, prefill
from ray_tpu.obs import context as trace_context
from ray_tpu.obs import recorder as trace_recorder
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.llm.engine")


def prefix_cache_hit_counter():
    """Prompt tokens served from the prefix cache instead of recomputed,
    split by the TIER that held them (hbm = resident paged cache,
    host / object = resurrected by llm/kvtier with zero recompute).
    Alongside the lookup counter it gives the fleet-level hit rate the
    disaggregated decode pick consumes (llm/disagg/orchestrator.py);
    the tier label is the `== kv tiers ==` mix `ray_tpu status` shows."""
    from ray_tpu.util.metrics import Counter

    return Counter(
        "llm_prefix_cache_hit_tokens_total",
        description="prompt tokens whose KV was reused from the prefix "
        "cache at prefill admission (no recompute), by serving tier "
        "(hbm/host/object)",
        tag_keys=("model", "tier"),
    )


def prefix_cache_lookup_counter():
    from ray_tpu.util.metrics import Counter

    return Counter(
        "llm_prefix_cache_lookup_tokens_total",
        description="prompt tokens considered for prefix-cache reuse at "
        "prefill admission (hit_tokens / lookup_tokens = hit rate)",
        tag_keys=("model",),
    )


def preemption_counter():
    """Requests kicked out of a running batch, attributable per tenant:
    `reason` separates KV-pressure preemptions (pressure), priority
    preemptions where a paying tenant displaced a batch tenant
    (priority), and crash-recovery re-enqueues (recover). The tenant
    label is what makes a fleet's noisy-neighbor story auditable — the
    batch tenant's preempt rate should rise while the paying tenant's
    stays flat."""
    from ray_tpu.obs.telemetry import cluster_counter

    return cluster_counter(
        "llm_preemptions_total",
        description="requests preempted out of the decode batch, by "
        "model, tenant, and reason (pressure/priority/recover)",
        tag_keys=("model", "tenant", "reason"),
    )


def utilization_gauges() -> dict:
    """Per-engine utilization gauges for the cluster telemetry plane
    (obs/telemetry.py): the fleet view the SLO-driven autoscaler sizes
    pools from. All aggregate by SUM across engines/replicas."""
    from ray_tpu.obs.telemetry import cluster_gauge

    return {
        "kv_pages_used": cluster_gauge(
            "llm_kv_pages_used",
            description="paged-KV blocks currently allocated in this "
            "engine (used = total - free)",
            tag_keys=("model",),
        ),
        "kv_pages_total": cluster_gauge(
            "llm_kv_pages_total",
            description="paged-KV blocks this engine was configured with",
            tag_keys=("model",),
        ),
        "kv_hbm_bytes": cluster_gauge(
            "llm_kv_hbm_bytes",
            description="bytes of accelerator memory held by this "
            "engine's paged KV cache (static allocation)",
            tag_keys=("model",),
        ),
        "queue_depth": cluster_gauge(
            "llm_queue_depth",
            description="requests waiting for prefill admission in this "
            "engine",
            tag_keys=("model",),
        ),
        "running": cluster_gauge(
            "llm_running_requests",
            description="requests in this engine's decode batch",
            tag_keys=("model",),
        ),
    }


def register_metrics() -> None:
    """scripts/check_metrics.py hook: force lazy metrics to register."""
    prefix_cache_hit_counter()
    prefix_cache_lookup_counter()
    preemption_counter()
    utilization_gauges()


class AdapterSlotsExhausted(ValueError):
    """Every LoRA adapter slot is loaded and none can be evicted (all
    referenced by in-flight requests, or eviction was not requested).
    Subclasses ValueError so pre-r21 callers matching on the generic
    add_lora failure keep working; fleet routing catches THIS type to
    fall back to another replica instead of treating it as a bad
    request."""


@dataclasses.dataclass
class EngineConfig:
    model: llama.LlamaConfig = dataclasses.field(default_factory=lambda: llama.LLAMA_TINY)
    num_blocks: int = 512
    block_size: int = 16
    max_num_seqs: int = 16          # decode batch ceiling
    max_prefill_len: int = 1024     # longest admitted prompt suffix
    attn_impl: str = "auto"
    cache_dtype: Any = None          # default: model dtype
    enable_prefix_caching: bool = True
    eos_token_id: int = 2
    # tensor-parallel serving: a MeshSpec (e.g. MeshSpec(tp=2)) shards
    # weights Megatron-style and the paged KV cache across its kv-head
    # dim; XLA inserts the TP collectives (reference: vLLM TP degree ->
    # placement group, vllm_models.py:117-131 — here it's one SPMD
    # program over the mesh, no worker gang)
    mesh_spec: Any = None
    # LoRA multiplexing: serve up to max_loras adapters from ONE engine
    # with mixed-adapter continuous batching — every sequence in a decode
    # batch may use a different adapter (reference: per-replica adapter
    # load/unload, llm/_internal/serve/deployments/llm/multiplex/)
    max_loras: int = 0
    lora_rank: int = 8
    lora_targets: tuple = ("wq", "wv")
    # multi-step decode: run up to this many decode+sample iterations ON
    # DEVICE per host round-trip (llm/decode_loop.py). 1 = classic
    # one-sync-per-token stepping. Chunks shrink automatically near a
    # request's max_tokens/max_seq; EOS overshoot is discarded host-side.
    # With pipeline_decode this is only the adaptive controller's
    # STARTING chunk; measured host-gap/device-step times take over.
    decode_chunk: int = 8
    # pipelined decode (llm/pipeline.py): batch state lives on device
    # across chunks, stop conditions evaluate in-graph (finished rows
    # freeze + all-done early-out), and chunk N+1 dispatches before
    # chunk N's tokens are synced so host bookkeeping overlaps device
    # compute; chunk length adapts to the measured host gap. Token
    # streams are bitwise-identical to the sync path. False keeps the
    # classic sync path (also taken automatically for batches with
    # > pipeline.STOP_WIDTH_CAP stop ids, and by spec decoding, which
    # has its own round structure).
    pipeline_decode: bool = True
    # profile=True: every decode round trip lands in the
    # llm_decode_chunk_ms histogram + timeline (ray_tpu.profiler
    # surfaces); profile_decode() gives the full roofline breakdown
    profile: bool = False
    # speculative decoding (llm/spec/): a SpecConfig turns each decode
    # round into draft -> one batched verify pass (k+1 tokens per row
    # through the paged prefill path) -> distribution-preserving
    # accept/resample. Rows whose drafter proposes nothing degenerate to
    # a plain decode step inside the same program; if NO row has a
    # draft, the round falls back to the classic decode/chunk path.
    spec: Any = None
    # tiered prefix cache (llm/kvtier): sealed full blocks evicted from
    # the HBM allocator spill to a host-DRAM LRU and then the object
    # store instead of being discarded, and prefill admission resurrects
    # them with a verified scatter (zero recompute). True / a dict / a
    # KVTierConfig enables it; None keeps the HBM-only cache.
    kvtier: Any = None
    # mixed ragged batching (llm/mixed.py over ops/ragged.py): pack
    # in-flight prefill chunks AND the running decode batch into ONE
    # ragged dispatch per step instead of separate prefill/decode
    # programs — prompts stream mixed_prefill_chunk tokens/step so a
    # long prefill never stalls decode rows. Token streams stay bitwise
    # identical to the split path (retained as the identity oracle);
    # spec verify also routes through the packed ragged program,
    # deleting the rectangular verify's per-row pad-column waste.
    mixed_batch: bool = False
    mixed_prefill_chunk: int = 256

    def __post_init__(self):
        if isinstance(self.model, str):
            # registry name ("llama3-8b", "mistral-7b", ...) — the vLLM
            # model-id ergonomics (models/registry.py)
            from ray_tpu.models.registry import get_model_config

            self.model = get_model_config(self.model)
        from ray_tpu.models.moe import MoEConfig

        if isinstance(self.model, MoEConfig):
            # the serving decoder is the dense llama path; accepting a
            # MoEConfig (a LlamaConfig subclass) would silently serve a
            # dense model with the experts' hyperparameters
            raise ValueError(
                "LLMEngine serves dense llama-family models; MoE serving "
                "is not implemented (training-side MoE lives in models/moe.py)"
            )
        # a prefill bucket longer than the context window can never be
        # used; clamping keeps bucket compilation bounded by the model
        self.max_prefill_len = min(self.max_prefill_len, self.model.max_seq)
        # chunk lengths compile per value: clamp to the bounded bucket
        # set so the jit cache can never grow past it
        from ray_tpu.llm.pipeline import CHUNK_BUCKETS

        self.decode_chunk = min(self.decode_chunk, CHUNK_BUCKETS[-1])
        # the ragged kernel's static max_q_len compiles per value: one
        # clamped budget keeps the mixed program count at exactly one
        self.mixed_prefill_chunk = max(
            1, min(self.mixed_prefill_chunk, self.max_prefill_len)
        )
        if self.spec is not None:
            from ray_tpu.llm.spec import SpecConfig

            if isinstance(self.spec, dict):
                self.spec = SpecConfig(**self.spec)
            if not isinstance(self.spec, SpecConfig):
                raise ValueError(
                    f"EngineConfig.spec must be a SpecConfig, got {type(self.spec)}"
                )
        if self.kvtier is not None:
            from ray_tpu.llm.kvtier import KVTierConfig

            if self.kvtier is True:
                self.kvtier = KVTierConfig()
            elif isinstance(self.kvtier, dict):
                self.kvtier = KVTierConfig(**self.kvtier)
            if not isinstance(self.kvtier, KVTierConfig):
                raise ValueError(
                    f"EngineConfig.kvtier must be a KVTierConfig, True, or a "
                    f"dict, got {type(self.kvtier)}"
                )

    def prefill_buckets(self) -> list[int]:
        out, b = [], 16
        while b < self.max_prefill_len:
            out.append(b)
            b *= 2
        out.append(self.max_prefill_len)
        return out

    def decode_buckets(self) -> list[int]:
        out, b = [], 1
        while b < self.max_num_seqs:
            out.append(b)
            b *= 2
        out.append(self.max_num_seqs)
        return out

    @property
    def max_blocks_per_seq(self) -> int:
        return -(-self.model.max_seq // self.block_size)


class RequestStatus:
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED = "finished"
    ABORTED = "aborted"
    # exported to another engine via a KV handoff (disaggregated
    # prefill/decode); this engine no longer owns the request
    MIGRATED = "migrated"


@dataclasses.dataclass
class Request:
    request_id: str
    prompt_token_ids: list
    sampling_params: SamplingParams
    output_token_ids: list = dataclasses.field(default_factory=list)
    status: str = RequestStatus.WAITING
    seq: Optional[SequenceBlocks] = None
    arrival: float = dataclasses.field(default_factory=time.time)
    finish_reason: Optional[str] = None
    num_preemptions: int = 0
    cumulative_logprob: float = 0.0
    token_logprobs: list = dataclasses.field(default_factory=list)
    lora_slot: int = 0
    # multi-tenant QoS (ray_tpu.fleet): higher priority admits first and
    # may preempt lower-priority running requests; tenant labels the
    # preempt/shed counters; slo_tag (when set) records this request's
    # SLO observations under an EXTRA series beyond the engine's
    # model_tag — the fleet grades canary replicas and tenants from it
    priority: int = 0
    tenant: str = ""
    slo_tag: Optional[str] = None
    _key: Any = None
    # request tracing (ray_tpu.obs): the submitter's TraceContext; every
    # lifecycle span below records as its child. Timestamps: queue_start
    # resets on preemption (each wait is its own queue_wait span);
    # first_prefill/first_token survive preemption (they ARE the SLOs);
    # span_cursor tiles decode-round spans so per-request phase spans
    # cover arrival -> finish without gaps (scheduler gaps land inside a
    # round span and are priced by its sched_gap_ms attr, not hidden)
    trace: Any = None
    t_queue_start: float = 0.0
    t_first_prefill: Optional[float] = None
    t_prefill_start: Optional[float] = None
    t_first_token: Optional[float] = None
    t_span_cursor: Optional[float] = None
    _prefill_cached: int = 0

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_token_ids) + len(self.output_token_ids)


@dataclasses.dataclass
class RequestOutput:
    request_id: str
    new_token_ids: list
    output_token_ids: list
    finished: bool
    finish_reason: Optional[str] = None
    num_cached_tokens: int = 0


class LLMEngine:
    def __init__(
        self,
        config: EngineConfig,
        params: Optional[llama.Params] = None,
        seed: int = 0,
    ):
        self.config = config
        c = config
        self.params = (
            params
            if params is not None
            else llama.init_params(c.model, jax.random.key(seed))
        )
        self.allocator = BlockAllocator(c.num_blocks, c.block_size)
        self.mesh = None
        if c.mesh_spec is not None:
            from ray_tpu.parallel.mesh import make_mesh
            from ray_tpu.parallel.sharding import default_rules, tree_shardings

            self.mesh = make_mesh(c.mesh_spec)
            tp = self.mesh.shape["tp"]
            if c.model.n_kv_heads % max(tp, 1) != 0:
                raise ValueError(
                    f"n_kv_heads={c.model.n_kv_heads} not divisible by tp={tp}"
                )
            rules = default_rules()
            self.params = jax.device_put(
                self.params,
                tree_shardings(self.mesh, rules, llama.logical_axes(c.model)),
            )
        self.cache = self._init_kv_cache()
        # static KV allocation size for the llm_kv_hbm_bytes gauge
        # (nbytes is array metadata; no device sync)
        self._kv_cache_nbytes = int(sum(
            getattr(x, "nbytes", 0) for x in jax.tree.leaves(self.cache)
        ))
        self._telemetry_next = 0.0  # gauge-refresh throttle
        self.waiting: deque[Request] = deque()
        self.running: list[Request] = []
        self.requests: dict[str, Request] = {}  # unfinished only
        self.num_preemptions = 0
        self._counter = itertools.count()
        self._root_key = jax.random.key(seed ^ 0x5EED)
        # serving SLO label (llm_ttft_seconds{model=...}); the OpenAI app
        # stamps its model_id here after construction
        self.model_tag = "engine"
        # weight-sync plane (train/weight_sync.py): the version of the
        # last applied publish — 0 until a subscriber swaps params.
        # Surfaced via stats()/GET /v1/stats so actor/learner skew in an
        # RL post-training deployment is observable from one RPC.
        self.weight_version = 0

        # LoRA adapter stacks: slot 0 is the zero adapter ("no lora");
        # per-target A [L, n_slots, d_in, r], B [L, n_slots, r, d_out]
        self._lora_slots: dict[str, int] = {}
        # lora_id -> last time a request selected it (monotonic): the
        # LRU order evict_lru_lora / add_lora(evict=True) walk when the
        # slot budget is exhausted
        self._lora_last_used: dict[str, float] = {}
        self._lora = None
        if c.max_loras > 0:
            m = c.model
            n = c.max_loras + 1
            out_dims = {
                "wq": m.n_heads * m.head_dim,
                "wk": m.n_kv_heads * m.head_dim,
                "wv": m.n_kv_heads * m.head_dim,
            }
            stacks = {}
            for t in c.lora_targets:
                stacks[f"{t}_A"] = jnp.zeros(
                    (m.n_layers, n, m.d_model, c.lora_rank), m.dtype
                )
                stacks[f"{t}_B"] = jnp.zeros(
                    (m.n_layers, n, c.lora_rank, out_dims[t]), m.dtype
                )
            self._lora = stacks

        # jitted entry points; cache buffers are donated so XLA updates pages
        # in place instead of copying the whole cache every step
        self._prefill = jax.jit(
            lambda params, t, p, sl, sm, bt, cl, cache, lora: prefill(
                params, t, p, sl, sm, bt, cl, cache, c.model,
                block_size=c.block_size, lora=lora,
            ),
            donate_argnums=(7,),
        )
        self._decode = jax.jit(
            lambda params, t, p, sm, bt, cl, cache, lora: decode_step(
                params, t, p, sm, bt, cl, cache, c.model,
                block_size=c.block_size, attn_impl=c.attn_impl, lora=lora,
            ),
            donate_argnums=(6,),
        )
        self._decode_chunks: dict[tuple, Any] = {}  # (n_steps, mode) -> jitted
        # disaggregated serving: jitted KV-page scatter per padded width
        # (import_handoff), and prefix-cache accounting for stats()/the
        # decode-replica pick (hit/lookup in TOKENS, not blocks)
        self._kv_imports: dict[int, Any] = {}
        self.prefix_hit_tokens = 0
        self.prefix_lookup_tokens = 0
        # hit tokens split by serving tier (hbm resident vs host/object
        # resurrected) — the per-tier view stats()/metrics expose
        self.tier_hit_tokens: dict[str, int] = {}
        self.num_prefill_batches = 0
        self.num_kv_imports = 0

        # tiered prefix cache (llm/kvtier): listens to the allocator's
        # seal/evict/drop events, owns the host-DRAM + object-store
        # tiers, and publishes this engine's resident chains to the
        # cluster prefix index when one is attached
        self.kvtier = None
        self.kvfetch = None
        if c.kvtier is not None:
            from ray_tpu.llm.kvtier import KVTierManager

            self.kvtier = KVTierManager(self, c.kvtier)
            # prefetch-at-admission + cross-engine pulls (llm/kvfetch):
            # the worker verifies/deserializes/fetches a queued
            # request's prefix while it waits; step()'s tick scatters
            # it into HBM before the request reaches the queue head
            from ray_tpu.llm.kvfetch import KVFetchManager

            self.kvfetch = KVFetchManager(self)

        # pipelined decode (llm/pipeline.py): device-resident batch
        # state, the in-flight double-buffered chunk, the adaptive chunk
        # controller, and outputs produced by internal flushes (returned
        # by the next step() so no token/finish event is ever dropped)
        self._pipe_state = None
        self._pipe_inflight = None
        self._pipe_ctl = None
        self._pipe_stats = None
        self._pipe_last_sync_t = None
        self._pending_outputs: list[RequestOutput] = []

        # speculative decoding: drafter + verify program cache + stats
        self.drafter = None
        self.spec_stats = None
        self._verify_fns: dict[int, Any] = {}  # suffix width K+1 -> jitted
        if c.spec is not None:
            from ray_tpu.llm.spec.stats import SpecStats

            self.drafter = c.spec.build_drafter(c.model)
            self.spec_stats = SpecStats()

        # mixed ragged batching (llm/mixed.py): prefill cursors
        # (request_id -> next un-prefilled absolute token index — a
        # request in here is RUNNING but mid-prompt), the ONE jitted
        # ragged dispatch, the lazily-built ragged spec verifier, and
        # padding-waste stats. The cursor dict exists unconditionally so
        # the preempt/abort/recover hooks never need a mode check.
        self._mixed_prefills: dict[str, int] = {}
        self._mixed_fn = None
        self._mixed_stats = None
        self._verify_ragged = None
        if c.mixed_batch:
            from ray_tpu.llm.mixed import MixedStats
            from ray_tpu.models.llama_decode import mixed_step

            maxq = c.mixed_prefill_chunk
            self._mixed_fn = jax.jit(
                lambda params, t, p, sl, bt, cu, cl, cache, lora: mixed_step(
                    params, t, p, sl, bt, cu, cl, cache, c.model,
                    block_size=c.block_size, max_q_len=maxq,
                    attn_impl=c.attn_impl, lora=lora,
                ),
                donate_argnums=(7,),
            )
            self._mixed_stats = MixedStats()

    def _init_kv_cache(self):
        """Fresh paged KV cache with the engine's sharding (also the
        crash-recovery rebuild path: recover(rebuild_kv=True))."""
        c = self.config
        cache = init_cache(
            c.model, c.num_blocks * c.block_size, dtype=c.cache_dtype,
            trash_slots=c.block_size,
        )
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # cache [L, kv_heads, slots, hd]: heads across tp
            kv_sharding = NamedSharding(self.mesh, P(None, "tp", None, None))
            cache = jax.tree.map(
                lambda x: jax.device_put(x, kv_sharding), cache
            )
        return cache

    @staticmethod
    def _assert_chunk_bucket(n_steps: int) -> None:
        """The (n_steps, mode) jit caches are bounded BY CONSTRUCTION to
        the adaptive bucket set — a novel n_steps would silently compile
        (and retain) a new program forever."""
        from ray_tpu.llm.pipeline import CHUNK_BUCKETS

        assert n_steps in CHUNK_BUCKETS, (
            f"decode chunk n_steps={n_steps} outside the bounded bucket "
            f"set {CHUNK_BUCKETS}; quantize via pipeline.chunk_bucket"
        )

    def _decode_chunk_fn(self, n_steps: int, sample_mode: str = "full"):
        c = self.config
        self._assert_chunk_bucket(n_steps)
        fn = self._decode_chunks.get((n_steps, sample_mode))
        if fn is None:
            from ray_tpu.llm.decode_loop import decode_chunk

            fn = jax.jit(
                lambda params, t, p, bt, cl, cache, temps, tks, tps, keys,
                starts, remaining, lora:
                decode_chunk(
                    params, t, p, bt, cl, cache, temps, tks, tps, keys,
                    starts, remaining,
                    c.model, n_steps=n_steps, block_size=c.block_size,
                    trash_slot=c.num_blocks * c.block_size,
                    attn_impl=c.attn_impl, sample_mode=sample_mode, lora=lora,
                ),
                donate_argnums=(5,),
            )
            self._decode_chunks[(n_steps, sample_mode)] = fn
        return fn

    def _pipe_chunk_fn(self, n_steps: int, sample_mode: str, stop_w: int):
        """Jitted masked/early-exiting chunk (llm/pipeline.py) for the
        pipelined path; cache keyed (and bounded) by the chunk-bucket +
        stop-width sets."""
        c = self.config
        self._assert_chunk_bucket(n_steps)
        from ray_tpu.llm.pipeline import STOP_WIDTHS, decode_chunk_masked

        assert stop_w in STOP_WIDTHS, (
            f"stop width {stop_w} outside the bounded set {STOP_WIDTHS}"
        )
        key = (n_steps, sample_mode, "masked", stop_w)
        fn = self._decode_chunks.get(key)
        if fn is None:
            fn = jax.jit(
                lambda params, t, p, bt, cl, cache, temps, tks, tps, keys,
                starts, max_toks, done, stop_ids, stop_on_eos, lora:
                decode_chunk_masked(
                    params, t, p, bt, cl, cache, temps, tks, tps, keys,
                    starts, max_toks, done, stop_ids, stop_on_eos,
                    c.model, n_steps=n_steps, block_size=c.block_size,
                    trash_slot=c.num_blocks * c.block_size,
                    eos_id=c.eos_token_id, attn_impl=c.attn_impl,
                    sample_mode=sample_mode, lora=lora,
                ),
                donate_argnums=(5,),
            )
            self._decode_chunks[key] = fn
        return fn

    def _verify_fn(self, width: int):
        """Jitted spec verifier for a [B_pad, width] suffix (width = k+1,
        a compile-time bucket like decode_buckets)."""
        c = self.config
        fn = self._verify_fns.get(width)
        if fn is None:
            from ray_tpu.models.llama_decode import verify_tokens

            fn = jax.jit(
                lambda params, t, p, sm, bt, cl, cache, lora: verify_tokens(
                    params, t, p, sm, bt, cl, cache, c.model,
                    block_size=c.block_size, lora=lora,
                ),
                donate_argnums=(6,),
            )
            self._verify_fns[width] = fn
        return fn

    def _verify_ragged_fn(self):
        """Jitted PACKED spec verifier (llama_decode.verify_tokens_ragged):
        rows carry exactly 1 + draft_len tokens instead of a [B, K+1]
        rectangle — jax.jit re-specializes per packed-token bucket, so
        one entry covers every (T_pad, B_pad) shape."""
        if self._verify_ragged is None:
            c = self.config
            from ray_tpu.models.llama_decode import verify_tokens_ragged

            maxq = c.spec.num_draft_tokens + 1
            self._verify_ragged = jax.jit(
                lambda params, t, p, sl, bt, cu, cl, gi, cache, lora:
                verify_tokens_ragged(
                    params, t, p, sl, bt, cu, cl, gi, cache, c.model,
                    block_size=c.block_size, max_q_len=maxq,
                    attn_impl=c.attn_impl, lora=lora,
                ),
                donate_argnums=(8,),
            )
        return self._verify_ragged

    @staticmethod
    def _sample_mode(batch) -> str:
        """STATIC sampler fast path for this batch (llm.sampling): the
        full top-k/top-p machinery costs a per-step lax.top_k; greedy
        and plain-temperature batches skip it entirely. A request with
        top_k > TOP_CAP forces the exact full-vocab sort — the capped
        path would silently clamp it (ADVICE r05).

        Per-row greedy short-circuit: top-k/top-p cannot change an
        argmax (the most-likely token always survives both filters), so
        a greedy request's knobs are IGNORED when deriving the mode —
        clients routinely send temperature=0 together with top_k/top_p,
        and before this, one such request dragged the whole batch onto a
        sort path nobody sampled from."""
        sampled = [r for r in batch if not r.sampling_params.greedy]
        if not sampled:
            return "greedy"
        if all(
            r.sampling_params.top_k <= 0 and r.sampling_params.top_p >= 1.0
            for r in sampled
        ):
            return "categorical"
        if any(r.sampling_params.needs_full_sort for r in sampled):
            return "full_sort"
        return "full"

    # -- LoRA multiplexing ----------------------------------------------------

    def add_lora(self, lora_id: str, adapters: dict,
                 evict: bool = False) -> None:
        """Register an adapter: {"wq": (A [L,d,r], B [L,r,out]), ...} for
        the configured lora_targets. Requests select it by lora_id.

        With ``evict`` a full slot budget evicts the least-recently-used
        resident adapter first (refusing any with in-flight requests);
        without it — or when nothing is evictable — raises
        :class:`AdapterSlotsExhausted`."""
        c = self.config
        if c.max_loras <= 0:
            raise ValueError("EngineConfig.max_loras is 0: LoRA disabled")
        if lora_id in self._lora_slots:
            raise ValueError(f"lora {lora_id!r} already loaded")
        if len(self._lora_slots) >= c.max_loras:
            if not evict or not self.evict_lru_lora():
                raise AdapterSlotsExhausted(
                    f"all {c.max_loras} adapter slots in use"
                )
        # validate EVERYTHING before mutating: a partial write would leave
        # stale weights in a slot still marked free
        for t, (A, B) in adapters.items():
            if t not in c.lora_targets:
                raise ValueError(
                    f"adapter target {t!r} not in lora_targets={c.lora_targets}"
                )
            want_a = self._lora[f"{t}_A"].shape[0:1] + self._lora[f"{t}_A"].shape[2:]
            want_b = self._lora[f"{t}_B"].shape[0:1] + self._lora[f"{t}_B"].shape[2:]
            if tuple(np.shape(A)) != want_a or tuple(np.shape(B)) != want_b:
                raise ValueError(
                    f"adapter {t!r} shapes {np.shape(A)}/{np.shape(B)} != "
                    f"expected {want_a}/{want_b}"
                )
        used = set(self._lora_slots.values())
        slot = next(i for i in range(1, c.max_loras + 1) if i not in used)
        for t, (A, B) in adapters.items():
            self._lora[f"{t}_A"] = self._lora[f"{t}_A"].at[:, slot].set(
                jnp.asarray(A, self.config.model.dtype)
            )
            self._lora[f"{t}_B"] = self._lora[f"{t}_B"].at[:, slot].set(
                jnp.asarray(B, self.config.model.dtype)
            )
        self._lora_slots[lora_id] = slot
        self._lora_last_used[lora_id] = time.monotonic()

    def remove_lora(self, lora_id: str) -> None:
        slot = self._lora_slots.get(lora_id)
        if slot is None:
            raise ValueError(f"unknown lora {lora_id!r}")
        in_flight = [
            r.request_id for r in list(self.waiting) + self.running
            if r.lora_slot == slot
        ]
        if in_flight:
            # zeroing the slot mid-generation would silently switch those
            # sequences to the base model
            raise ValueError(
                f"lora {lora_id!r} is in use by requests {in_flight[:4]}; "
                "abort or drain them first"
            )
        self._lora_slots.pop(lora_id)
        self._lora_last_used.pop(lora_id, None)
        for k in list(self._lora):
            self._lora[k] = self._lora[k].at[:, slot].set(0.0)
        # cached prefixes salted with this slot would serve the NEXT
        # adapter assigned to it stale K/V — but only THIS slot's chains:
        # other adapters' cached prefixes (and their deep-tier copies)
        # are still correct and survive the swap
        self.allocator.drop_prefix_cache(salt=slot)

    def evict_lru_lora(self) -> Optional[str]:
        """Evict the least-recently-used resident adapter that has no
        in-flight requests referencing its slot. Returns the evicted
        lora_id, or None when every resident adapter is pinned by
        in-flight work (the caller decides whether that is
        AdapterSlotsExhausted or a retry)."""
        busy = {r.lora_slot for r in list(self.waiting) + self.running}
        candidates = sorted(
            (lid for lid, slot in self._lora_slots.items()
             if slot not in busy),
            key=lambda lid: self._lora_last_used.get(lid, 0.0),
        )
        if not candidates:
            return None
        victim = candidates[0]
        self.remove_lora(victim)
        logger.info("evicted LRU adapter %r", victim)
        return victim

    def _lora_slot(self, lora_id) -> int:
        if lora_id is None:
            return 0
        try:
            return self._lora_slots[lora_id]
        except KeyError:
            raise ValueError(f"unknown lora {lora_id!r}; add_lora first") from None

    def _lora_arg(self, ids: "np.ndarray") -> "dict | None":
        if self._lora is None:
            return None
        # stacks are [L, n_slots, ...]; the scan consumes the layer dim
        return {"ids": jnp.asarray(ids, jnp.int32), **self._lora}

    # -- public API -----------------------------------------------------------

    def add_request(
        self,
        prompt_token_ids: list,
        sampling_params: Optional[SamplingParams] = None,
        request_id: Optional[str] = None,
        lora_id: Optional[str] = None,
        trace: Optional[trace_context.TraceContext] = None,
        priority: int = 0,
        tenant: str = "",
        slo_tag: Optional[str] = None,
    ) -> str:
        sp = sampling_params or SamplingParams()
        rid = request_id or f"req-{next(self._counter)}"
        lora_slot = self._lora_slot(lora_id)
        if lora_id is not None:
            self._lora_last_used[lora_id] = time.monotonic()
        if len(prompt_token_ids) > self.config.max_prefill_len:
            raise ValueError(
                f"prompt length {len(prompt_token_ids)} exceeds "
                f"max_prefill_len={self.config.max_prefill_len}"
            )
        # must leave room for >=1 generated token: a prompt of max_seq or
        # longer would overflow the block table (sized for max_seq) during
        # prefill and push RoPE positions past the table
        if len(prompt_token_ids) >= self.config.model.max_seq:
            raise ValueError(
                f"prompt length {len(prompt_token_ids)} >= model max_seq="
                f"{self.config.model.max_seq}; prompts must be shorter than "
                "the model context window"
            )
        # a prompt the cache can NEVER hold would wedge the queue head:
        # _prefill_one would return None forever while the engine spins
        need = self.allocator.blocks_needed(len(prompt_token_ids) + 1)
        if need > self.config.num_blocks:
            raise ValueError(
                f"prompt needs {need} KV blocks but the cache has only "
                f"{self.config.num_blocks}; raise num_blocks or shorten it"
            )
        req = Request(rid, list(map(int, prompt_token_ids)), sp)
        req.lora_slot = lora_slot
        req.priority = int(priority)
        req.tenant = tenant
        req.slo_tag = slo_tag
        # every request is traced: explicit ctx from the serving layer, the
        # ambient contextvar (submitter thread), or a fresh root — the
        # flight recorder is bounded, so always-on costs a dict per request
        req.trace = trace or trace_context.current() or trace_context.new_context()
        req.t_queue_start = req.arrival
        key = self._root_key if sp.seed is None else jax.random.key(sp.seed)
        req._key = jax.random.fold_in(key, hash(rid) & 0x7FFFFFFF)
        self.requests[rid] = req
        self.waiting.append(req)
        if self.kvfetch is not None:
            # kick the prefix prefetch while the request waits in the
            # queue (deep-tier verify/deserialize + any remote fetch
            # happen on the worker, off the admission path)
            self.kvfetch.request_admitted(req)
        return rid

    def abort_request(self, request_id: str) -> None:
        req = self.requests.get(request_id)
        if req is None or req.status in (RequestStatus.FINISHED, RequestStatus.ABORTED):
            return
        if self.kvfetch is not None:
            # cancel/flush discipline: an abort mid-prefetch releases
            # the request's reservation refs and staged chain NOW — an
            # abort storm must leak zero blocks and zero endpoint slots
            self.kvfetch.cancel(request_id)
        if req in self.running:
            # removing a decode-batch row is a membership change: land
            # the in-flight pipelined chunk first (its outputs are
            # delivered by the next step()); the flush may finish this
            # request normally, in which case there is nothing to abort
            self._pipe_flush(deliver=True)
            if req.status in (RequestStatus.FINISHED, RequestStatus.ABORTED):
                return
        if req in self.running:
            self.running.remove(req)
        if req in self.waiting:
            self.waiting.remove(req)
        self._mixed_prefills.pop(request_id, None)
        if req.seq is not None:
            req.seq.release()
        req.status = RequestStatus.ABORTED
        req.finish_reason = "abort"
        now = time.time()
        self._obs_span(
            req, "llm.request", req.arrival, now,
            {"request_id": req.request_id, "finish_reason": "abort",
             "prompt_tokens": len(req.prompt_token_ids),
             "output_tokens": len(req.output_token_ids),
             "e2e_s": round(max(0.0, now - req.arrival), 6)},
        )
        try:
            from ray_tpu.obs import slo

            slo.record_request_slo(
                self.model_tag, ttft_s=None, tpot_s=None, queue_wait_s=None,
                e2e_s=max(0.0, now - req.arrival), finish_reason="abort",
            )
        except Exception:  # noqa: BLE001
            pass
        self.requests.pop(request_id, None)
        if self.drafter is not None:
            self.drafter.release(request_id)

    def has_unfinished(self) -> bool:
        # _pending_outputs counts: an internal pipeline flush (abort /
        # handoff) may have finished the LAST running request — its
        # finish event still needs a step() call to deliver, and every
        # driver loop gates step() on this predicate
        return bool(self.waiting or self.running or self._pending_outputs)

    def step(self) -> list[RequestOutput]:
        """One engine iteration: admit + prefill waiting requests, else decode.

        ALL admissible prefills are dispatched back-to-back and sampled
        in one batch with a single host sync — per-request syncing cost
        ~150 ms/prefill on the tunneled device (round-5 profile), ~5 s
        of a 32-request benchmark."""
        if _chaos.ACTIVE is not None:
            for _f in _chaos.fire(
                "llm.engine.step",
                kinds=(_chaos.PREEMPT_ENGINE, _chaos.KILL_WORKER,
                       _chaos.DELAY_RPC),
                running=len(self.running), waiting=len(self.waiting),
            ):
                if _f.kind in (_chaos.PREEMPT_ENGINE, _chaos.KILL_WORKER):
                    # engine dies before mutating this round's state — the
                    # owner (e.g. openai_api._EngineRunner) recovers via
                    # recover() and re-enqueues in-flight requests
                    raise _chaos.EnginePreempted(
                        "chaos: engine preempted mid-step"
                    )
                if _f.kind == _chaos.DELAY_RPC:
                    # deterministic engine slowdown: overload tests build
                    # real queue depth without racing wall-clock
                    time.sleep(_f.delay_s)
        now_m = time.monotonic()
        if now_m >= self._telemetry_next:
            # throttled gauge refresh: a few dict writes per ~200ms, not
            # per decode step
            self._telemetry_next = now_m + 0.2
            self.update_telemetry_gauges()
        if self._pending_outputs:
            # outputs produced by an internal pipeline flush (abort /
            # handoff / recovery forced a sync outside step()): deliver
            # before doing anything else so no finish event is dropped
            out, self._pending_outputs = self._pending_outputs, []
            return out
        if self.kvfetch is not None:
            # land completed prefetches BEFORE the admission check: the
            # scatter registers the blocks with reservation refs, so
            # the queue head's match_prefix finds its prefix resident
            # and _admission_need discounts the live-shared blocks
            self.kvfetch.tick()
        if self.waiting:
            # QoS admission order: the highest-priority waiting request
            # is admitted first (stable — strictly FIFO when priorities
            # are uniform, i.e. every pre-fleet deployment)
            self._promote_priority()
            head = self.waiting[0]
            if head.priority > 0 and self.running and (
                len(self.running) >= self.config.max_num_seqs
                or self._admission_need(head) > self.allocator.num_free
            ):
                # priority preemption: a paying tenant's request blocked
                # on batch-slot or KV pressure displaces the lowest-
                # priority running request (a batch tenant's decode /
                # prefill) through the normal preempt/recover ladder —
                # the victim recomputes, nothing is lost
                victim = min(
                    self.running, key=lambda r: (r.priority, -r.arrival)
                )
                if victim.priority < head.priority:
                    flushed = self._pipe_flush()
                    if flushed:
                        return flushed
                    self._preempt_one(
                        below_priority=head.priority, reason="priority"
                    )
                    # the victim re-queued at the head: restore QoS order
                    # so the admission check below sees the paying tenant
                    self._promote_priority()
        if self.config.mixed_batch:
            # unified dispatch: admission + in-flight prefill chunks +
            # every decode row in ONE ragged program (llm/mixed.py);
            # steps with no prefill work fall through to the regular
            # decode ladder inside _mixed_step
            return self._mixed_step()
        if (
            self.waiting
            and len(self.running) < self.config.max_num_seqs
            # cheap read-only precheck: can the head of the queue
            # actually admit? Free blocks must cover its (recompute)
            # prompt MINUS live-shared prefix-cache hits, which adopt
            # by refcount and cost no free blocks. Without the check, a
            # block-starved waiting queue would flush the pipeline (and
            # force a full DeviceBatchState rebuild) every round just
            # to fail admission again; without the cache discount, a
            # prefix-sharing request would starve behind a free-pool
            # check its cache hit satisfies
            and self._admission_need(self.waiting[0])
            <= self.allocator.num_free
        ):
            # admission is a membership change: the in-flight pipelined
            # chunk (dispatched for the OLD batch) must land first
            flushed = self._pipe_flush()
            if flushed:
                return flushed
            admitted: list = []  # (req, last-token logits [1, V]) pairs
            while self.waiting and len(self.running) < self.config.max_num_seqs:
                got = self._prefill_one()
                if got is None:
                    break  # no cache room: decode to free blocks
                admitted.append(got)
            if admitted:
                reqs = [r for r, _ in admitted]
                logits = jnp.concatenate([l for _, l in admitted], axis=0)
                tok, logprob = self._sample_batch(logits, reqs)
                t1 = time.time()  # host sync done: first token exists
                outputs = self._append_tokens(reqs, tok, logprob)
                for r in reqs:
                    self._obs_span(
                        r, "engine.prefill",
                        r.t_prefill_start if r.t_prefill_start is not None else t1,
                        t1,
                        {"prompt_tokens": len(r.prompt_token_ids),
                         "cached_tokens": r._prefill_cached,
                         "recompute": r.num_preemptions > 0},
                    )
                    if r.t_first_token is None:
                        r.t_first_token = t1
                    r.t_span_cursor = t1
                self._obs_finalize(reqs, t1)
                return outputs
        if self.running:
            return self._decode_step()
        return []

    def recover(self, *, rebuild_kv: bool = False) -> list[str]:
        """Crash/preemption recovery: push every RUNNING request back to
        the head of the waiting queue with its generated prefix intact.

        Finished-prefix safety falls out of the preemption-recompute
        contract _preempt_one already honors: re-admission prefills
        ``prompt + output_token_ids``, so nothing generated is lost and
        nothing re-emits (callers see only tokens appended past the
        prefix). ``rebuild_kv=True`` additionally discards the allocator
        and KV cache (a crash of unknown provenance may have torn them);
        the prefix cache dies with them, correctness doesn't.

        Returns the re-enqueued request ids (post-mortem / logging)."""
        # the in-flight pipelined chunk may BE what crashed: drop it
        # un-synced (its tokens were never booked, so the re-admission
        # recompute covers exactly the delivered prefix)
        self._pipe_drop()
        # mid-prefill mixed cursors die with the batch: re-admission
        # recomputes each prompt from scratch (or its cached prefix)
        self._mixed_prefills.clear()
        now = time.time()
        victims = sorted(self.running, key=lambda r: r.arrival, reverse=True)
        self.running.clear()
        # orphan sweep: a crash INSIDE admission (after waiting.popleft,
        # before running.append) leaves a live request in neither deque —
        # without this it would never be stepped again and its caller
        # would hang forever
        queued = {r.request_id for r in victims} | {
            r.request_id for r in self.waiting
        }
        for r in self.requests.values():
            if (r.request_id not in queued
                    and r.status in (RequestStatus.WAITING,
                                     RequestStatus.RUNNING)):
                victims.append(r)
        if self.kvfetch is not None:
            # staged prefetch chains and reservations may reference the
            # state that just crashed: drop them (deep-tier copies stay
            # resurrectable); with rebuild_kv the block ids die with the
            # allocator and must NOT be freed into the new one
            self.kvfetch.reset(forget_blocks=rebuild_kv)
        if rebuild_kv:
            c = self.config
            self.allocator = BlockAllocator(c.num_blocks, c.block_size)
            self.cache = self._init_kv_cache()
            if self.kvtier is not None:
                # fresh allocator: re-attach the tier listeners and drop
                # the (now wrong) HBM metadata; spilled host/object
                # copies were sealed from correct pages and stay usable
                self.kvtier.rebind_allocator()
            for r in victims:
                r.seq = None  # blocks died with the old allocator
        moved = []
        for r in victims:
            if r.seq is not None:
                try:
                    r.seq.release()
                except Exception:  # noqa: BLE001 — torn allocator state
                    pass
            r.seq = None
            r.status = RequestStatus.WAITING
            r.num_preemptions += 1
            self.num_preemptions += 1
            try:
                preemption_counter().inc(
                    1, tags={"model": self.model_tag,
                             "tenant": r.tenant or "",
                             "reason": "recover"}
                )
            except Exception:  # noqa: BLE001
                pass
            r.t_queue_start = now
            r.t_span_cursor = None
            self.waiting.appendleft(r)  # reversed-arrival: oldest ends up first
            if self.drafter is not None:
                self.drafter.release(r.request_id)
            self._obs_span(r, "engine.recover", now, now,
                           {"rebuild_kv": rebuild_kv,
                            "output_tokens": len(r.output_token_ids)})
            moved.append(r.request_id)
        if moved:
            logger.warning(
                "engine recovered: re-enqueued %d in-flight request(s)%s",
                len(moved), " with fresh KV cache" if rebuild_kv else "",
            )
        return moved

    # -- disaggregated prefill/decode (ray_tpu.llm.disagg) --------------------
    # A prefill-role engine runs _prefill_one + first-token sampling, then
    # EXPORTS the sequence (KV pages + request state) instead of decoding
    # it; a decode-role engine IMPORTS it with zero recompute. The wire
    # unit is llm/disagg/handoff.KVHandoff; transports live in
    # llm/disagg/connector.py. Invariant both sides rely on: a request
    # with num_tokens N has KV written for positions 0..N-2 (the newest
    # sampled token is fed — and its KV written — by the NEXT step).

    def kv_cache_device(self):
        """The device this engine's paged KV cache lives on — the fabric
        transport endpoint for device-direct imports (registering the
        cache's own device makes the final import hop zero-copy)."""
        return next(iter(self.cache["k"].devices()))

    def peek_prefix_tokens(self, prompt_token_ids: list,
                           lora_id: Optional[str] = None) -> int:
        """Read-only probe: prompt tokens a prefix-cache hit would cover
        on THIS engine (the disagg decode pick's cache-awareness signal)."""
        return self.allocator.probe_prefix(
            list(map(int, prompt_token_ids)), self._lora_slot(lora_id)
        )

    def peek_prefix_tiered(self, prompt_token_ids: list,
                           lora_id: Optional[str] = None) -> dict:
        """Read-only TIERED probe: the longest contiguous prefix of the
        prompt this engine can serve without recompute across ALL tiers
        (HBM resident + host/object resurrectable), with the
        tier-discounted score prefix-aware routing ranks replicas by.
        Returns {"n_tokens", "discounted", "by_tier"}."""
        tokens = list(map(int, prompt_token_ids))
        salt = self._lora_slot(lora_id)
        if self.kvtier is not None:
            return self.kvtier.probe_tiers(tokens, salt)
        n = self.allocator.probe_prefix(tokens, salt)
        return {"n_tokens": n, "discounted": float(n),
                "by_tier": ({"hbm": n} if n else {})}

    def drop_prefix_cache(self, salt: Optional[int] = None) -> None:
        """Invalidate the prefix cache across EVERY tier: the HBM
        allocator's reuse pool, the host-DRAM and object-store spill
        tiers, and this engine's rows in the cluster prefix index (an
        empty snapshot ships immediately). ``salt`` scopes the drop to
        one adapter's chains (fleet canary swap) — other tenants' cached
        prefixes survive. The one entry point a weight
        swap must call — dropping HBM alone would leave deeper tiers
        serving K/V computed with the OLD weights."""
        # the allocator's drop_listener cascades into the tier manager
        self.allocator.drop_prefix_cache(salt=salt)

    def export_request(self, request_id: str, keep_on_device: bool = False):
        """Export a RUNNING request as a KVHandoff and drop local
        ownership. The request's blocks are released (full prompt blocks
        stay resurrectable in this engine's prefix cache — a re-prefill
        after a lost transfer hits them); callers transfer the handoff
        and import it on a decode engine. With ``keep_on_device`` the
        gathered pages stay device arrays (the fabric's device-direct
        path: the handoff is device-sealed and never staged through
        host RAM; use ``handoff.to_host()`` if an RPC edge ends up
        carrying it after all)."""
        # the exported pages must reflect the host's view of num_tokens:
        # land any in-flight pipelined chunk before gathering
        self._pipe_flush(deliver=True)
        from ray_tpu.llm.disagg.handoff import KVHandoff

        req = self.requests.get(request_id)
        if req is None or req.status != RequestStatus.RUNNING or req.seq is None:
            raise ValueError(
                f"request {request_id!r} is not RUNNING on this engine "
                "(only admitted, in-flight requests can be exported)"
            )
        if request_id in self._mixed_prefills:
            # mid-prompt mixed row: KV exists only up to the cursor, not
            # the num_tokens-1 positions the handoff invariant promises
            raise ValueError(
                f"request {request_id!r} is mid-prefill in a mixed batch; "
                "export after its prompt chunks complete"
            )
        c = self.config
        n_kv = req.num_tokens - 1  # positions with KV written
        slots = req.seq.slots_for_range(0, n_kv)
        # pad the gather to a power-of-two width (compiled-shape
        # bucketing on TPU); pad rows read the trash page and are
        # sliced off host-side after the device->host copy (device-side
        # on the keep_on_device path — the slice is a device op)
        width = max(1, 1 << (n_kv - 1).bit_length()) if n_kv else 1
        num_slots = c.num_blocks * c.block_size
        sl = np.full(width, num_slots, np.int32)
        sl[:n_kv] = slots
        sl = jnp.asarray(sl)
        if keep_on_device:
            k_pages = self.cache["k"][:, :, sl, :][:, :, :n_kv, :]
            v_pages = self.cache["v"][:, :, sl, :][:, :, :n_kv, :]
        else:
            k_pages = np.asarray(self.cache["k"][:, :, sl, :])[:, :, :n_kv, :]
            v_pages = np.asarray(self.cache["v"][:, :, sl, :])[:, :, :n_kv, :]
        lora_id = None
        if req.lora_slot:
            lora_id = next(
                (lid for lid, s in self._lora_slots.items() if s == req.lora_slot),
                None,
            )
        handoff = KVHandoff(
            request_id=req.request_id,
            prompt_token_ids=list(req.prompt_token_ids),
            output_token_ids=list(req.output_token_ids),
            sampling_params=req.sampling_params,
            key_data=np.asarray(jax.random.key_data(req._key)),
            num_kv_tokens=n_kv,
            k_pages=k_pages,
            v_pages=v_pages,
            model_sig=(c.model.n_layers, c.model.n_kv_heads, c.model.head_dim),
            lora_id=lora_id,
            cumulative_logprob=req.cumulative_logprob,
            token_logprobs=list(req.token_logprobs),
            t_arrival=req.arrival,
            t_first_prefill=req.t_first_prefill,
            t_first_token=req.t_first_token,
            # span-tiling: the llm.kv_transfer span starts where the
            # prefill span ended, so the request's phase spans stay
            # gap-free across the hop (obs coverage gate)
            t_export=(req.t_span_cursor if req.t_span_cursor is not None
                      else time.time()),
            trace=req.trace.to_dict() if req.trace is not None else None,
        )
        handoff.seal(device=keep_on_device)
        # drop local ownership; sealed full blocks stay in the prefix cache
        self.running.remove(req)
        req.seq.release()
        req.seq = None
        req.status = RequestStatus.MIGRATED
        self.requests.pop(request_id, None)
        if self.drafter is not None:
            self.drafter.release(request_id)
        return handoff

    def _kv_import_fn(self, width: int):
        fn = self._kv_imports.get(width)
        if fn is None:
            fn = jax.jit(
                lambda cache, k, v, slots: {
                    "k": cache["k"].at[:, :, slots, :].set(k),
                    "v": cache["v"].at[:, :, slots, :].set(v),
                },
                donate_argnums=(0,),
            )
            self._kv_imports[width] = fn
        return fn

    def _scatter_block_pages(self, k, v, blocks: list) -> None:
        """Scatter position-ordered host pages [L, KVH, n_kv, D] into
        whole ``blocks`` with ONE jitted set (power-of-two padded, pad
        rows hit the trash page). The single recipe tier resurrection
        (_resurrect_tiers) and the prefetch tick share — the scatter
        shape must never drift between them."""
        c = self.config
        bs = c.block_size
        n_kv = int(k.shape[2])
        width = max(1, 1 << (n_kv - 1).bit_length())
        num_slots = c.num_blocks * bs
        sl = np.full(width, num_slots, np.int32)  # pad rows hit the trash page
        pos = 0
        for b in blocks:
            sl[pos:pos + bs] = np.arange(b * bs, (b + 1) * bs)
            pos += bs
        dt = self.cache["k"].dtype
        kp = np.zeros(k.shape[:2] + (width,) + k.shape[3:], k.dtype)
        vp = np.zeros_like(kp)
        kp[:, :, :n_kv] = k
        vp[:, :, :n_kv] = v
        self.cache = self._kv_import_fn(width)(
            self.cache, jnp.asarray(kp, dt), jnp.asarray(vp, dt),
            jnp.asarray(sl),
        )

    def import_handoff(self, handoff,
                       trace: Optional[trace_context.TraceContext] = None) -> str:
        """Adopt an exported request: scatter its KV pages into this
        engine's paged cache and enqueue it RUNNING — no prefill, no
        recompute (`num_cached_tokens` covers every transferred
        position). Raises NoFreeBlocksError when the cache can't hold it
        right now (callers may retry after decode frees blocks) and
        ValueError on a model/cache mismatch."""
        # joining the decode batch is a membership change: land the
        # in-flight pipelined chunk so the import sees settled state
        self._pipe_flush(deliver=True)
        c = self.config
        sig = (c.model.n_layers, c.model.n_kv_heads, c.model.head_dim)
        if tuple(handoff.model_sig) != sig:
            raise ValueError(
                f"handoff model signature {tuple(handoff.model_sig)} != "
                f"engine {sig}; prefill and decode pools must serve the "
                "same model"
            )
        rid = handoff.request_id
        if rid in self.requests:
            raise ValueError(f"request {rid!r} already live on this engine")
        n_kv = handoff.num_kv_tokens
        if handoff.k_pages.shape[2] != n_kv or handoff.v_pages.shape[2] != n_kv:
            raise ValueError(
                f"handoff KV pages cover {handoff.k_pages.shape[2]} tokens, "
                f"header says {n_kv}"
            )
        req = Request(rid, list(map(int, handoff.prompt_token_ids)),
                      handoff.sampling_params)
        req.output_token_ids = list(map(int, handoff.output_token_ids))
        req.cumulative_logprob = handoff.cumulative_logprob
        req.token_logprobs = list(handoff.token_logprobs)
        req.lora_slot = self._lora_slot(handoff.lora_id)
        req._key = jax.random.wrap_key_data(jnp.asarray(handoff.key_data))
        req.trace = (
            trace
            or trace_context.TraceContext.from_dict(handoff.trace)
            or trace_context.new_context()
        )
        req.arrival = handoff.t_arrival
        req.t_queue_start = handoff.t_arrival
        req.t_first_prefill = handoff.t_first_prefill
        req.t_first_token = handoff.t_first_token

        seq = SequenceBlocks(self.allocator)
        seq.chain = req.lora_slot  # salt the hash chain like _prefill_one
        seq.ensure_capacity(req.num_tokens)  # may raise NoFreeBlocksError
        width = max(1, 1 << (n_kv - 1).bit_length()) if n_kv else 1
        num_slots = c.num_blocks * c.block_size
        sl = np.full(width, num_slots, np.int32)  # pad rows hit the trash page
        sl[:n_kv] = seq.slots_for_range(0, n_kv)
        dt = self.cache["k"].dtype
        if isinstance(handoff.k_pages, jax.Array):
            # fabric device path: the pages arrived as device arrays on
            # this engine's endpoint device — pad and scatter entirely
            # on-device, never staging the multi-MB payload through host
            # RAM (device_put here is the final hop when the transport
            # endpoint differs from the cache's device)
            cache_devs = self.cache["k"].devices()
            kp, vp = handoff.k_pages, handoff.v_pages
            if kp.devices() != cache_devs:
                dev = next(iter(cache_devs))
                kp = jax.device_put(kp, dev)
                vp = jax.device_put(vp, dev)
            pad = [(0, 0), (0, 0), (0, width - n_kv), (0, 0)]
            k = jnp.pad(kp.astype(dt), pad)
            v = jnp.pad(vp.astype(dt), pad)
        else:
            k = np.zeros(
                handoff.k_pages.shape[:2] + (width,) + handoff.k_pages.shape[3:],
                handoff.k_pages.dtype)
            v = np.zeros_like(k)
            k[:, :, :n_kv] = handoff.k_pages
            v[:, :, :n_kv] = handoff.v_pages
        self.cache = self._kv_import_fn(width)(
            self.cache, jnp.asarray(k, dt), jnp.asarray(v, dt), jnp.asarray(sl)
        )
        seq.num_tokens = req.num_tokens
        # every transferred position counts as cached: zero recompute
        seq.num_cached_tokens = n_kv
        if c.enable_prefix_caching:
            # seal transferred full blocks so future prompts sharing this
            # prefix hit THIS engine's cache too
            written = req.prompt_token_ids + req.output_token_ids[:-1]
            seq.seal_full_blocks(written)
        req.seq = seq
        req.status = RequestStatus.RUNNING
        self.requests[rid] = req
        self.running.append(req)
        self.num_kv_imports += 1
        req.t_span_cursor = time.time()  # decode rounds tile from import
        return rid

    def generate(
        self,
        prompts: list,
        sampling_params: "SamplingParams | list[SamplingParams] | None" = None,
    ) -> list:
        """Blocking batch generation; returns output token lists in order."""
        if sampling_params is None or isinstance(sampling_params, SamplingParams):
            sampling_params = [sampling_params or SamplingParams()] * len(prompts)
        rids = [
            self.add_request(p, sp) for p, sp in zip(prompts, sampling_params)
        ]
        finals: dict[str, list] = {}
        while self.has_unfinished():
            for out in self.step():
                if out.finished:
                    finals[out.request_id] = out.output_token_ids
        return [finals[r] for r in rids]

    def update_telemetry_gauges(self) -> None:
        """Refresh this engine's utilization gauges (KV-page occupancy,
        HBM bytes, queue depth) in the process registry — the series the
        telemetry plane ships cluster-wide. Called throttled from step()
        and by TelemetryReporter collect callbacks; must never throw into
        the serving path."""
        try:
            g = utilization_gauges()
            tags = {"model": self.model_tag}
            c = self.config
            g["kv_pages_used"].set(c.num_blocks - self.allocator.num_free,
                                   tags=tags)
            g["kv_pages_total"].set(c.num_blocks, tags=tags)
            g["kv_hbm_bytes"].set(self._kv_cache_nbytes, tags=tags)
            g["queue_depth"].set(len(self.waiting), tags=tags)
            g["running"].set(len(self.running), tags=tags)
            if self.kvtier is not None:
                self.kvtier.update_gauges()
                # piggyback the prefix-index snapshot on the same
                # throttle (telemetry-style freshness, no extra timer)
                self.kvtier.flush_index()
        except Exception:  # noqa: BLE001 — observability must not break serving
            pass

    def stats(self) -> dict:
        out = {
            "num_waiting": len(self.waiting),
            "num_running": len(self.running),
            "free_blocks": self.allocator.num_free,
            "total_blocks": self.config.num_blocks,
            "num_prefill_batches": self.num_prefill_batches,
            "weight_version": self.weight_version,
            "prefix_cache": {
                "hit_tokens": self.prefix_hit_tokens,
                "lookup_tokens": self.prefix_lookup_tokens,
                "hit_rate": (
                    round(self.prefix_hit_tokens / self.prefix_lookup_tokens, 4)
                    if self.prefix_lookup_tokens else 0.0
                ),
                "by_tier": dict(self.tier_hit_tokens),
            },
        }
        if self.kvtier is not None:
            # the tier breakdown GET /v1/stats surfaces (rides
            # engine.stats() through the serving layer unchanged)
            out["kv_tiers"] = self.kvtier.stats()
            if self.kvfetch is not None:
                # prefetch/fetch rollup rides the same surface
                out["kv_tiers"]["fetch"] = self.kvfetch.stats()
        if self.num_kv_imports:
            out["num_kv_imports"] = self.num_kv_imports
        if self.spec_stats is not None:
            out["spec"] = self.spec_stats.to_dict()
        if self._pipe_stats is not None and self._pipe_stats.dispatches:
            # the `pipeline` row of /v1/stats: chunk-size distribution,
            # host/device split, overlap ratio, early-exit savings
            out["pipeline"] = self._pipe_stats.to_dict()
        if self._mixed_stats is not None and self._mixed_stats.dispatches:
            # the mixed ragged dispatch's padding-waste accounting (the
            # --mixed bench's padding_waste_ratio reads this row)
            out["mixed"] = self._mixed_stats.to_dict()
        return out

    def profile_decode(
        self,
        *,
        batch_size: Optional[int] = None,
        context_len: Optional[int] = None,
        iters: int = 8,
        warmup: int = 2,
        include_prefill: bool = True,
        export_observability: bool = True,
    ):
        """Roofline-attributed StepProfile of one decode step of THIS
        engine (its weights, block size, attention impl), over a scratch
        paged cache — live sequences and the real KV cache are untouched.

        Segments: embed / qkv_rope / kv_write / kv_read_attn / block_mlp
        / lm_head / sampling / host_sync (+ standalone prefill probe).
        The report is the serving-side counterpart of the train-step
        profile: it shows how far decode sits from the HBM roofline and
        which slice to attack first."""
        from ray_tpu.profiler import profile_decode_step

        c = self.config
        B = batch_size or min(4, c.max_num_seqs)
        ctx = context_len or min(32, c.model.max_seq - 1)
        return profile_decode_step(
            c.model, self.params,
            batch_size=B, context_len=ctx, block_size=c.block_size,
            attn_impl=c.attn_impl, iters=iters, warmup=warmup,
            include_prefill=include_prefill,
            export_observability=export_observability,
            meta={"engine_num_blocks": c.num_blocks,
                  "engine_decode_chunk": c.decode_chunk},
        )

    def profile_spec_decode(
        self,
        *,
        batch_size: Optional[int] = None,
        context_len: Optional[int] = None,
        iters: int = 6,
        warmup: int = 2,
        export_observability: bool = True,
    ):
        """Roofline-attributed StepProfile of one SPECULATIVE round of
        this engine (draft -> verify -> accept -> kv_rollback rungs),
        over a scratch paged cache + allocator — live state untouched.
        Requires EngineConfig.spec."""
        if self.config.spec is None:
            raise ValueError("EngineConfig.spec is None: spec decoding disabled")
        from ray_tpu.profiler import profile_spec_decode_step

        c = self.config
        B = batch_size or min(4, c.max_num_seqs)
        ctx = context_len or min(
            32, c.model.max_seq - c.spec.num_draft_tokens - 2
        )
        return profile_spec_decode_step(
            c.model, self.params, c.spec,
            batch_size=B, context_len=ctx, block_size=c.block_size,
            iters=iters, warmup=warmup,
            export_observability=export_observability,
            meta={"engine_num_blocks": c.num_blocks},
        )

    # -- request tracing (ray_tpu.obs) ---------------------------------------
    # Per-request lifecycle spans into the flight recorder + SLO
    # histograms. Phases tile: queue_wait [arrival/preempt -> prefill
    # dispatch], prefill [dispatch -> first token], then one span per
    # decode round (chunk or spec) from the request's span cursor — so a
    # retrieved trace covers the full e2e wall-clock; host scheduling
    # gaps are priced inside each round span as sched_gap_ms, never
    # hidden. Every hook swallows failures: observability must not
    # break decode.

    def _obs_span(self, req, name: str, t0: float, t1: float,
                  attrs: Optional[dict] = None, status: str = "ok") -> None:
        try:
            trace_recorder.get_recorder().record(
                name, t0, t1, ctx=req.trace, attrs=attrs, status=status
            )
        except Exception:  # noqa: BLE001
            pass

    def _obs_decode_round(self, batch: list, outputs: list, wall0: float,
                          name: str, n_steps: int,
                          extra: Optional[dict] = None) -> list:
        """Record one decode round for every participating request, then
        finalize the ones that finished. ``extra`` maps request_id ->
        additional span attrs (spec rounds attach draft/accept counts)."""
        try:
            t1 = time.time()
            active_ms = round((t1 - wall0) * 1e3, 3)
            by_rid = {o.request_id: o for o in outputs}
            for r in batch:
                out = by_rid.get(r.request_id)
                start = r.t_span_cursor if r.t_span_cursor is not None else wall0
                start = min(start, wall0)
                attrs = {
                    "n_steps": n_steps,
                    "new_tokens": len(out.new_token_ids) if out else 0,
                    "active_ms": active_ms,
                }
                gap_ms = (wall0 - start) * 1e3
                if gap_ms > 0.05:
                    attrs["sched_gap_ms"] = round(gap_ms, 3)
                if extra:
                    attrs.update(extra.get(r.request_id, ()))
                self._obs_span(r, name, start, t1, attrs)
                r.t_span_cursor = t1
            self._obs_finalize(batch, t1)
        except Exception:  # noqa: BLE001
            pass
        return outputs

    def _obs_finalize(self, reqs: list, t_end: float) -> None:
        """Root span + SLO observations for requests that just finished."""
        for r in reqs:
            if r.status != RequestStatus.FINISHED:
                continue
            try:
                n_out = len(r.output_token_ids)
                e2e = max(0.0, t_end - r.arrival)
                ttft = (
                    max(0.0, r.t_first_token - r.arrival)
                    if r.t_first_token is not None else None
                )
                tpot = (
                    (t_end - r.t_first_token) / (n_out - 1)
                    if r.t_first_token is not None and n_out > 1 else None
                )
                queue_wait = (
                    max(0.0, r.t_first_prefill - r.arrival)
                    if r.t_first_prefill is not None else None
                )
                prefill_span = (
                    max(0.0, r.t_first_token - r.t_first_prefill)
                    if r.t_first_token is not None
                    and r.t_first_prefill is not None else None
                )
                attrs = {
                    "request_id": r.request_id,
                    "finish_reason": r.finish_reason,
                    "prompt_tokens": len(r.prompt_token_ids),
                    "output_tokens": n_out,
                    "num_preemptions": r.num_preemptions,
                    "e2e_s": round(e2e, 6),
                }
                if ttft is not None:
                    attrs["ttft_s"] = round(ttft, 6)
                if tpot is not None:
                    attrs["tpot_s"] = round(tpot, 6)
                if queue_wait is not None:
                    attrs["queue_wait_s"] = round(queue_wait, 6)
                self._obs_span(r, "llm.request", r.arrival, t_end, attrs)
                from ray_tpu.obs import slo

                slo.record_request_slo(
                    self.model_tag,
                    ttft_s=ttft, tpot_s=tpot, queue_wait_s=queue_wait,
                    e2e_s=e2e, finish_reason=r.finish_reason or "",
                    prefill_span_s=prefill_span,
                )
                if r.slo_tag and r.slo_tag != self.model_tag:
                    # fleet QoS/canary plane: the same observation under
                    # the request's own tag (a tenant or a canary
                    # replica) so evaluate_slo can grade it in isolation
                    slo.record_request_slo(
                        r.slo_tag,
                        ttft_s=ttft, tpot_s=tpot, queue_wait_s=queue_wait,
                        e2e_s=e2e, finish_reason=r.finish_reason or "",
                        prefill_span_s=prefill_span,
                    )
            except Exception:  # noqa: BLE001
                pass

    # -- scheduling internals -------------------------------------------------

    def _admission_need(self, req) -> int:
        """Free-pool blocks admitting ``req`` would actually consume
        (kv_cache.probe_admission_need over the recompute prompt, with
        the request's LoRA salt; the full count when prefix caching is
        off)."""
        if not self.config.enable_prefix_caching:
            return self.allocator.blocks_needed(req.num_tokens)
        return self.allocator.probe_admission_need(
            req.prompt_token_ids + req.output_token_ids, req.lora_slot
        )

    def _pad_to_bucket(self, n: int, buckets: list) -> int:
        for b in buckets:
            if n <= b:
                return b
        return buckets[-1]

    def _admit_one(self):
        """Admit the head of the waiting queue: prefix match (+ tiered
        resurrection), capacity reservation for the FULL recompute
        prompt, queue/hit bookkeeping — everything up to (but not
        including) dispatch, shared by the split prefill path
        (_prefill_one) and mixed admission (_mixed_admit). Returns
        (req, seq, prompt, matched) past the commit point, or None when
        the cache has no room (caller falls through to decode)."""
        c = self.config
        req = self.waiting[0]
        seq = SequenceBlocks(self.allocator)
        # after a preemption the recompute covers prompt + already-generated
        # tokens; outputs stay in output_token_ids so callers see them all
        prompt = req.prompt_token_ids + req.output_token_ids

        # prefix-cache hit: skip recomputing matched full blocks (always
        # leave >=1 token to prefill so we get next-token logits)
        matched_blocks: list = []
        matched = 0
        # adapters change K/V: salt the prefix-hash chain by lora slot so
        # sequences under different adapters never share cached blocks
        salt = req.lora_slot
        seq.chain = salt
        tier_counts: dict[str, int] = {}
        if c.enable_prefix_caching:
            blocks, matched, chain = self.allocator.match_prefix(prompt, salt)
            if matched >= len(prompt):
                # whole prompt cached — we still need last-token logits, so
                # re-match against prompt[:-1] to leave >=1 token to prefill
                self.allocator.free(blocks)
                blocks, matched, chain = self.allocator.match_prefix(prompt[:-1], salt)
            if matched:
                tier_counts["hbm"] = matched
            if self.kvtier is not None:
                # tiered resurrection: blocks past the HBM match may sit
                # spilled in host DRAM / the object store — scatter them
                # back (verified, zero recompute) and extend the match
                rblocks, rtokens, chain, rcounts = self._resurrect_tiers(
                    prompt, matched, chain, salt
                )
                if rblocks:
                    blocks = list(blocks) + rblocks
                    matched += rtokens
                    for t, n in rcounts.items():
                        tier_counts[t] = tier_counts.get(t, 0) + n
            if blocks:
                seq.adopt_prefix(blocks, chain, matched)
                matched_blocks = blocks

        suffix = prompt[matched:]
        try:
            seq.ensure_capacity(len(prompt))
        except NoFreeBlocksError:
            if matched_blocks:
                seq.release()
            return None  # no room: fall through to decode; retry later
        self.waiting.popleft()
        self.num_prefill_batches += 1
        if self.kvfetch is not None and matched:
            # blocks the prefetch tick scattered ahead of admission
            # match as HBM residents; re-attribute their hits to the
            # tier the prefetch pulled them from, so the per-tier mix
            # reflects where the KV actually came from. Taken only
            # PAST the admission commit point — an ensure_capacity
            # failure above leaves the attribution for the retry.
            for t, n in self.kvfetch.take_attribution(
                    req.request_id).items():
                move = min(n, tier_counts.get("hbm", 0))
                if move <= 0:
                    continue
                tier_counts["hbm"] -= move
                tier_counts[t] = tier_counts.get(t, 0) + move
            if tier_counts.get("hbm") == 0:
                tier_counts.pop("hbm", None)
        # prefix-cache accounting over the ORIGINAL prompt only: a
        # preemption recompute re-matching its own just-sealed blocks
        # would otherwise inflate the hit rate the decode pick trusts
        if req.num_preemptions == 0:
            self.prefix_lookup_tokens += len(req.prompt_token_ids)
            self.prefix_hit_tokens += min(matched, len(req.prompt_token_ids))
            for t, n in tier_counts.items():
                self.tier_hit_tokens[t] = self.tier_hit_tokens.get(t, 0) + n
            try:
                tags = {"model": self.model_tag}
                prefix_cache_lookup_counter().inc(
                    len(req.prompt_token_ids), tags=tags
                )
                for t, n in tier_counts.items():
                    prefix_cache_hit_counter().inc(
                        n, tags={"model": self.model_tag, "tier": t}
                    )
            except Exception:  # noqa: BLE001 — metrics must not break admission
                pass
        t_admit = time.time()
        self._obs_span(
            req, "engine.queue_wait", req.t_queue_start, t_admit,
            {"recompute": req.num_preemptions > 0},
        )
        req.t_prefill_start = t_admit
        if req.t_first_prefill is None:
            req.t_first_prefill = t_admit
        req._prefill_cached = matched
        return req, seq, prompt, matched

    def _prefill_one(self):
        """Prefill the head of the waiting queue: DISPATCH only, no host
        sync. Returns (req, last-token logits [1, V] device array), or
        None when the cache has no room (caller falls through to decode)."""
        got = self._admit_one()
        if got is None:
            return None
        req, seq, prompt, matched = got
        c = self.config

        num_slots = c.num_blocks * c.block_size
        bt = np.zeros((1, self._bt_width([len(seq.blocks)])), np.int32)
        bt[0, : len(seq.blocks)] = seq.blocks
        bt = jnp.asarray(bt)

        # chunked prefill: preemption recompute can exceed max_prefill_len;
        # each chunk extends context_lens, only the last chunk's logits count
        logits = None
        for start in range(matched, len(prompt), c.max_prefill_len):
            chunk = prompt[start : start + c.max_prefill_len]
            S_pad = self._pad_to_bucket(len(chunk), c.prefill_buckets())
            tokens = np.zeros((1, S_pad), np.int32)
            tokens[0, : len(chunk)] = chunk
            positions = np.zeros((1, S_pad), np.int32)
            positions[0, : len(chunk)] = np.arange(start, start + len(chunk))
            slots = np.full((1, S_pad), num_slots, np.int32)  # trash by default
            for i, p in enumerate(range(start, start + len(chunk))):
                slots[0, i] = seq.slot(p)
            logits, self.cache = self._prefill(
                self.params,
                jnp.asarray(tokens),
                jnp.asarray(positions),
                jnp.asarray([len(chunk)], jnp.int32),
                jnp.asarray(slots),
                bt,
                jnp.asarray([start + len(chunk)], jnp.int32),
                self.cache,
                self._lora_arg(np.asarray([req.lora_slot], np.int32)),
            )
        seq.num_tokens = len(prompt)
        if c.enable_prefix_caching:
            seq.seal_full_blocks(prompt)
        req.seq = seq
        req.status = RequestStatus.RUNNING
        self.running.append(req)
        if self.kvfetch is not None:
            # the sequence holds its own refs now: release the prefetch
            # reservation and book the lead time
            self.kvfetch.consumed(req.request_id)
        return req, logits

    # -- mixed ragged batching (ray_tpu.llm.mixed) ---------------------------
    # One ragged program per step serves in-flight prefill chunks AND
    # every decode row (llm/mixed.MixedBatchPlan over
    # llama_decode.mixed_step over ops/ragged). Prompts stream
    # mixed_prefill_chunk tokens per step, so decode rows advance every
    # step regardless of prompt length. The split path stays the
    # identity oracle: token streams must match it bitwise.

    def _mixed_admit(self):
        """Admit the queue head WITHOUT dispatching its prompt: the
        mixed dispatch feeds it chunk-by-chunk from the cursor this
        records. Returns the request or None (no cache room)."""
        got = self._admit_one()
        if got is None:
            return None
        req, seq, prompt, matched = got
        # seq.num_tokens tracks positions with K/V WRITTEN — exactly the
        # matched prefix until chunks land (the cursor advances it)
        seq.num_tokens = matched
        req.seq = seq
        req.status = RequestStatus.RUNNING
        self.running.append(req)
        self._mixed_prefills[req.request_id] = matched
        if self.kvfetch is not None:
            self.kvfetch.consumed(req.request_id)
        return req

    def _mixed_step(self) -> list[RequestOutput]:
        """One mixed-batch iteration (EngineConfig.mixed_batch): admit
        waiting requests, then serve every in-flight prefill chunk plus
        every decode row in ONE ragged dispatch. Steps with no prefill
        work route to the regular decode ladder — the degenerate
        all-q_len=1 case costs exactly the split path's decode step
        (including spec rounds and the pipelined chunk overlap)."""
        c = self.config
        if (
            self.waiting
            and len(self.running) < c.max_num_seqs
            # same read-only precheck as the split path: see step()
            and self._admission_need(self.waiting[0])
            <= self.allocator.num_free
        ):
            # admission is a membership change for the pipelined decode
            # carry: land the in-flight chunk first
            flushed = self._pipe_flush()
            if flushed:
                return flushed
            while self.waiting and len(self.running) < c.max_num_seqs:
                if self._mixed_admit() is None:
                    break  # no cache room: decode to free blocks
        if not self._mixed_prefills:
            return self._decode_step() if self.running else []
        # prefill chunks in flight: the unified dispatch replaces the
        # decode ladder this step, so the pipelined carry (dispatched
        # for the old all-decode batch) must land first
        flushed = self._pipe_flush()
        if flushed:
            return flushed
        wall0 = time.time()
        # KV for this step's writes: mid-prompt rows reserved their full
        # recompute prompt at admission; decode rows grow one position
        while True:
            try:
                for r in self.running:
                    if r.request_id not in self._mixed_prefills:
                        r.seq.ensure_capacity(r.num_tokens + 1)
                break
            except NoFreeBlocksError:
                if not self._preempt_one():
                    raise  # single running request can't fit: cache too small
        from ray_tpu.llm.mixed import MixedBatchPlan

        plan = MixedBatchPlan.build(self)
        logits, self.cache = self._mixed_fn(
            self.params,
            jnp.asarray(plan.tokens),
            jnp.asarray(plan.positions),
            jnp.asarray(plan.slots),
            jnp.asarray(plan.bt),
            jnp.asarray(plan.cu_q_lens),
            jnp.asarray(plan.context_lens),
            self.cache,
            self._lora_arg(plan.lora_ids),
        )
        plan.note(self._mixed_stats)

        # advance prefill cursors; a finishing prompt seals its full
        # blocks (the _prefill_one contract) and becomes a decode row
        done_set = set(plan.completes)
        prompt_done: list = []
        for row in range(plan.B):
            if plan.kinds[row] != "prefill":
                continue
            r = plan.reqs[row]
            end = plan.starts[row] + plan.chunk_lens[row]
            r.seq.num_tokens = end
            if row in done_set:
                if c.enable_prefix_caching:
                    r.seq.seal_full_blocks(
                        r.prompt_token_ids + r.output_token_ids
                    )
                del self._mixed_prefills[r.request_id]
                prompt_done.append(r)
            else:
                self._mixed_prefills[r.request_id] = end

        outputs: list[RequestOutput] = []
        if plan.emit_rows:
            emit_reqs = [plan.reqs[i] for i in plan.emit_rows]
            tok, logprob = self._sample_batch(
                logits[np.asarray(plan.emit_rows)], emit_reqs
            )
            t1 = time.time()  # host sync done
            outputs = self._append_tokens(emit_reqs, tok, logprob)
            for r in prompt_done:
                self._obs_span(
                    r, "engine.prefill",
                    r.t_prefill_start if r.t_prefill_start is not None else t1,
                    t1,
                    {"prompt_tokens": len(r.prompt_token_ids),
                     "cached_tokens": r._prefill_cached,
                     "recompute": r.num_preemptions > 0,
                     "mixed": True},
                )
                if r.t_first_token is None:
                    r.t_first_token = t1
                r.t_span_cursor = t1
            if prompt_done:
                self._obs_finalize(prompt_done, t1)
            dec = [
                j for j, i in enumerate(plan.emit_rows)
                if plan.kinds[i] == "decode"
            ]
            if dec:
                self._obs_decode_round(
                    [emit_reqs[j] for j in dec], [outputs[j] for j in dec],
                    wall0, "engine.mixed_round", 1,
                )
        return outputs

    def _resurrect_tiers(self, prompt: list, matched: int, chain: int,
                         salt: int) -> tuple:
        """Pull spilled full blocks past the HBM match back into the
        paged cache: walk the prompt's chain hashes from ``chain``,
        take each verified SpilledBlock from the deepest tiers, and
        scatter all their pages in ONE jitted set (the import_handoff
        shape — ``num_cached_tokens`` covers every resurrected position,
        zero recompute). A corrupt entry stops the walk (recompute from
        there); so does allocation pressure. Returns
        (blocks, n_tokens, chain, {tier: tokens})."""
        mgr = self.kvtier
        c = self.config
        bs = c.block_size
        # >=1 token must stay un-cached so prefill yields next-token
        # logits — the same contract the HBM whole-prompt re-match keeps
        limit = (len(prompt) - 1) // bs
        start = matched // bs
        entries: list[tuple] = []  # (hash, tier|"hbm", SpilledBlock|block_id)
        h = chain
        for i in range(start, limit):
            blk = tuple(prompt[i * bs : (i + 1) * bs])
            h2 = self.allocator.chain_hash(h, blk)
            got = mgr.take_verified(h2, blk)
            if got is None:
                # head-first eviction leaves mid-chain blocks RESIDENT
                # past a spilled head (match_prefix stopped at the gap):
                # adopt them by refcount instead of recomputing KV this
                # engine still holds (probe_tiers counts them; the
                # admission path must serve what routing advertises)
                b = self.allocator.lookup(h2)
                if b is None:
                    break
                entries.append((h2, "hbm", b))
            else:
                entries.append((h2, got[0], got[1]))
            h = h2
        deep = [e for e in entries if e[1] != "hbm"]
        if not entries or not deep:
            # nothing spilled to pull back: pure-HBM adoption would be
            # wrong here (these refs belong past a gap match_prefix
            # never saw ONLY when a deep block bridged it) — release
            if entries:
                self.allocator.free([b for _h, _t, b in entries])
            return [], 0, chain, {}
        try:
            new_blocks = self.allocator.allocate(len(deep))
        except NoFreeBlocksError:
            # deep entries stay spilled (take_verified is non-destructive
            # on success); adopted HBM refs must be returned
            self.allocator.free([b for _h, t, b in entries if t == "hbm"])
            return [], 0, chain, {}
        k = np.concatenate([sb.handoff.k_pages for _h, _t, sb in deep], axis=2)
        v = np.concatenate([sb.handoff.v_pages for _h, _t, sb in deep], axis=2)
        self._scatter_block_pages(k, v, new_blocks)
        tier_counts: dict[str, int] = {}
        blocks: list[int] = []
        it_new = iter(new_blocks)
        parent = chain
        for idx, (h2, tier, payload) in enumerate(entries):
            if tier == "hbm":
                blocks.append(payload)  # adopted resident block, ref held
            else:
                b = next(it_new)
                # re-register in HBM (the seal listener re-advertises the
                # hbm row) and drop the deep-tier copy it came from
                self.allocator.register_full_block(
                    b, h2, parent_hash=parent, tokens=payload.tokens,
                    n_prefix_tokens=(start + idx + 1) * bs,
                )
                mgr.promoted(h2, tier)
                blocks.append(b)
            tier_counts[tier] = tier_counts.get(tier, 0) + bs
            parent = h2
        for tier, n in tier_counts.items():
            if tier != "hbm":  # adopted residents are hits, not resurrections
                mgr.count_resurrected(tier, n)
        return blocks, len(entries) * bs, parent, tier_counts

    def _promote_priority(self) -> None:
        """Move the highest-priority waiting request to the queue head.
        Stable: FIFO within a priority class, and a no-op when
        priorities are uniform — the pre-fleet engine stays strictly
        FIFO."""
        w = self.waiting
        if len(w) < 2:
            return
        best_i = max(range(len(w)), key=lambda i: (w[i].priority, -i))
        if best_i:
            req = w[best_i]
            del w[best_i]
            w.appendleft(req)

    def _preempt_one(self, below_priority: Optional[int] = None,
                     reason: str = "pressure") -> bool:
        """Kick a running request back to waiting (recompute). The
        victim is the lowest-priority, newest-arrival request —
        identical to the historical newest-arrival pick when priorities
        are uniform. ``below_priority`` (the priority-preemption path)
        only preempts a victim strictly below it, and may empty the
        batch (the displacing request admits next round); the KV-
        pressure path keeps the >=2 guard so a batch of one can always
        make progress."""
        if not self.running:
            return False
        if below_priority is None and len(self.running) <= 1:
            return False
        victim = min(self.running, key=lambda r: (r.priority, -r.arrival))
        if below_priority is not None and victim.priority >= below_priority:
            return False
        try:
            preemption_counter().inc(
                1, tags={"model": self.model_tag,
                         "tenant": victim.tenant or "",
                         "reason": reason}
            )
        except Exception:  # noqa: BLE001 — accounting, not correctness
            pass
        self.running.remove(victim)
        # a mid-prefill mixed row re-queues like any victim: drop the
        # cursor; re-admission recomputes prompt+outputs from scratch
        self._mixed_prefills.pop(victim.request_id, None)
        victim.seq.release()
        victim.seq = None
        # outputs are kept; re-admission prefills prompt+outputs (recompute)
        victim.status = RequestStatus.WAITING
        victim.num_preemptions += 1
        self.num_preemptions += 1
        self.waiting.appendleft(victim)
        now = time.time()
        self._obs_span(victim, "engine.preempt", now, now,
                       {"num_preemptions": victim.num_preemptions,
                        "reason": reason})
        victim.t_queue_start = now  # next queue_wait span starts here
        victim.t_span_cursor = None
        if self.drafter is not None:
            # re-admission recomputes from scratch; stale draft-cache
            # state would desync from the recomputed sequence
            self.drafter.release(victim.request_id)
        logger.info("preempted %s (recompute)", victim.request_id)
        return True

    def _bt_width(self, page_counts) -> int:
        """Block-table width for this call: the batch's real page count
        rounded up to a power of two (compiled-shape bucketing), capped
        at the model maximum. Sizing to max_blocks_per_seq regardless of
        context made the paged kernel's grid iterate (and the XLA gather
        materialize) every POSSIBLE page — at short contexts that is an
        order of magnitude of wasted work per step."""
        w = max(list(page_counts) or [1])
        w = 1 << max(0, (w - 1)).bit_length()
        # floor: tiny width buckets would recompile as contexts grow past
        # each power of two right at the start of every run
        w = max(w, min(16, self.config.max_blocks_per_seq))
        return min(w, self.config.max_blocks_per_seq)

    def _chunk_steps(self) -> int:
        """Device-side steps this round: the configured chunk, shrunk so
        no running request can overrun max_tokens/max_seq, floored to a
        power of two (compiled-shape bucketing)."""
        c = self.config
        n = max(1, c.decode_chunk)
        for r in self.running:
            # only the HARD max_seq wall shrinks the chunk (positions past
            # it would index off the RoPE table). A request near its
            # max_tokens just overshoots and _append_chunk discards the
            # excess — throttling the whole batch to the shortest request
            # would reinstate the per-token host sync under staggered load
            n = min(n, max(1, c.model.max_seq - r.num_tokens))
        return 1 << (n.bit_length() - 1)

    def _remaining(self, r) -> int:
        """Output tokens this request can still KEEP (max_tokens budget)."""
        return max(1, r.sampling_params.max_tokens - len(r.output_token_ids))

    def _decode_step(self) -> list[RequestOutput]:
        if self.config.spec is not None:
            return self._spec_decode_step()
        if self.config.pipeline_decode:
            return self._pipelined_decode_step()
        return self._plain_decode_step()

    # -- pipelined decode (ray_tpu.llm.pipeline) ------------------------------
    # Chunk N+1 is dispatched from the device-resident carry BEFORE chunk
    # N's tokens are synced, so host bookkeeping overlaps device compute.
    # Membership changes (admission/abort/handoff/recovery) flush first;
    # rows that finish DURING the overlap are already `done` on device
    # (the stop ladder runs in-graph), so the early-dispatched chunk
    # computes the identical stream for live rows and nothing for dead
    # ones. Token identity vs the sync path is the contract.

    def _pipe_flush(self, deliver: bool = False) -> list[RequestOutput]:
        """Land the in-flight chunk (if any) and invalidate the
        device-resident state (callers flush precisely because
        membership is about to change). Returns the synced outputs;
        with ``deliver`` they are queued for the next step() instead."""
        rec, self._pipe_inflight = self._pipe_inflight, None
        self._pipe_state = None
        if rec is None:
            return []
        if self._pipe_stats is not None:
            self._pipe_stats.flushes += 1
        outs = self._pipe_sync(rec)
        # the gap to the next dispatch spans a membership change
        # (admission/prefill, abort, handoff) — none of it amortizes
        # with chunk length, so keep it out of the controller's
        # per-round overhead signal
        self._pipe_last_sync_t = None
        if deliver and outs:
            self._pending_outputs.extend(outs)
            return []
        return outs

    def _pipe_drop(self) -> None:
        """Crash-path reset: discard the in-flight chunk WITHOUT syncing
        (the device program may be the thing that died). Un-synced
        tokens were never booked into output_token_ids, so recovery's
        recompute-from-prefix contract holds."""
        self._pipe_inflight = None
        self._pipe_state = None
        self._pipe_last_sync_t = None

    def _pipelined_decode_step(self) -> list[RequestOutput]:
        from ray_tpu.llm import pipeline as pl

        c = self.config
        if self._pipe_ctl is None:
            self._pipe_ctl = pl.ChunkController(initial=max(1, c.decode_chunk))
            self._pipe_stats = pl.PipelineStats()
        if any(
            len(r.sampling_params.stop_token_ids) > pl.STOP_WIDTH_CAP
            for r in self.running
        ):
            # unbounded stop sets don't fit the padded on-device matrix;
            # serve this batch on the sync path (identical tokens)
            self._pipe_stats.sync_fallbacks += 1
            outs = self._pipe_flush()
            return outs if outs else self._plain_decode_step()

        t_prep0 = time.perf_counter()
        wall0 = time.time()
        prev = self._pipe_inflight
        self._pipe_inflight = None

        # chunk length: adaptive from the measured host round overhead
        # vs chunk wall, capped by the batch's largest remaining budget
        gap_ms = (
            (t_prep0 - self._pipe_last_sync_t) * 1e3
            if self._pipe_last_sync_t is not None else 0.0
        )
        cap = max((self._remaining(r) for r in self.running), default=1)
        n_steps = self._pipe_ctl.next_steps(cap=cap)

        # reserve KV for the chunk's writes (per-row clamped to budget
        # and the max_seq wall — done rows freeze in-graph, so the chunk
        # itself never needs the whole batch shrunk to the shortest row).
        # CRUCIALLY the horizon includes the un-synced in-flight chunk:
        # this dispatch continues from the device carry, which sits up
        # to prev_steps tokens past the host's num_tokens, and a write
        # past the reserved blocks would read block-table padding (0)
        # and clobber another sequence's block 0
        pending = prev["n_steps"] if prev is not None else 0
        try:
            for r in self.running:
                r.seq.ensure_capacity(
                    r.num_tokens + max(1, min(
                        pending + n_steps, self._remaining(r),
                        c.model.max_seq - r.num_tokens,
                    ))
                )
        except NoFreeBlocksError:
            # real cache pressure: preemption is a membership change —
            # land the in-flight chunk first so its tokens aren't lost,
            # then preempt and let the next round rebuild
            if prev is not None:
                self._pipe_inflight = prev
                return self._pipe_flush()
            self._pipe_state = None
            if not self._preempt_one():
                raise  # single running request can't fit: cache too small
            return []

        state = self._pipe_state
        if state is None:
            state = pl.DeviceBatchState.build(self, self.running)
            self._pipe_state = state
            if prev is None:
                self._pipe_stats.rebuilds += 1
        elif not state.refresh_block_tables(self.running):
            # a row outgrew the padded block-table width: flush + rebuild
            if prev is not None:
                self._pipe_inflight = prev
                return self._pipe_flush()
            state = pl.DeviceBatchState.build(self, self.running)
            self._pipe_state = state
            self._pipe_stats.rebuilds += 1

        # dispatch chunk N+1 from the device-resident carry (async: this
        # does NOT wait for chunk N)
        fn = self._pipe_chunk_fn(n_steps, state.sample_mode, state.stop_w)
        lora = None
        if self._lora is not None:
            lora = {"ids": state.lora_ids, **self._lora}
        t_dispatch = time.perf_counter()
        toks, lps, n_emit, steps_run, carry, self.cache = fn(
            self.params, state.tokens, state.positions, state.block_tables,
            state.context_lens, self.cache, state.temps, state.top_ks,
            state.top_ps, state.keys, state.starts, state.max_toks,
            state.done, state.stop_ids, state.stop_on_eos, lora,
        )
        state.adopt_carry(carry)
        host_prep_ms = (t_dispatch - t_prep0) * 1e3
        self._pipe_stats.record_dispatch(n_steps, host_prep_ms)
        if c.profile:
            pl.record_host_prep(host_prep_ms)
        self._pipe_inflight = {
            "batch": list(self.running),
            "row_of": dict(state.row_of),
            "toks": toks, "lps": lps, "n_emit": n_emit,
            "steps_run": steps_run, "n_steps": n_steps,
            "sample_mode": state.sample_mode,
            "t_dispatch": t_dispatch, "wall0": wall0, "gap_ms": gap_ms,
        }
        if prev is None:
            # cold start: nothing to overlap with yet; the next step()
            # dispatches chunk 2 and syncs this one
            return []
        return self._pipe_sync(prev)

    def _pipe_sync(self, rec) -> list[RequestOutput]:
        """Sync one dispatched chunk's tokens and run the host
        bookkeeping ladder for the rows still alive."""
        from ray_tpu.llm import pipeline as pl

        c = self.config
        t0 = time.perf_counter()
        toks = np.asarray(rec["toks"])          # the host sync
        lps = np.asarray(rec["lps"])
        n_emit = np.asarray(rec["n_emit"])
        steps_run = int(rec["steps_run"])
        t1 = time.perf_counter()
        self._pipe_last_sync_t = t1
        sync_wait_ms = (t1 - t0) * 1e3
        chunk_ms = (t1 - rec["t_dispatch"]) * 1e3
        self._pipe_ctl.note_overhead(rec["gap_ms"] + sync_wait_ms)
        self._pipe_ctl.note_chunk(chunk_ms, rec["n_steps"], steps_run)
        self._pipe_stats.record_sync(
            steps_run=steps_run, sync_wait_ms=sync_wait_ms, chunk_ms=chunk_ms
        )
        if c.profile:
            pl.record_sync_wait(sync_wait_ms)
            from ray_tpu.llm.decode_loop import record_chunk

            record_chunk(chunk_ms, rec["n_steps"], rec["sample_mode"],
                         len(rec["batch"]))
        # rows that finished in an earlier sync are done on device and
        # emitted nothing; only live rows get bookkeeping (their seq is
        # released on finish)
        live = [
            r for r in rec["batch"]
            if r.status == RequestStatus.RUNNING and r.seq is not None
        ]
        if not live:
            return []
        cols = [rec["row_of"][r.request_id] for r in live]
        outputs = self._append_chunk(
            live, toks[:, cols], lps[:, cols],
            row_counts=[int(n_emit[j]) for j in cols],
        )
        return self._obs_decode_round(
            live, outputs, rec["wall0"], "engine.decode_chunk",
            rec["n_steps"],
        )

    def _spec_decode_step(self) -> list[RequestOutput]:
        """One speculative round: draft -> one batched verify pass ->
        distribution-preserving accept -> KV rollback.

        Per-row fallback is IN-BATCH: a row whose drafter proposed
        nothing feeds only its current token (draft_len 0), its verify
        logits at column 0 are exactly a decode step's, and acceptance
        emits 1 token sampled from them. Only when no row at all has a
        draft does the round fall back to the plain decode/chunk path —
        paying the (k+1)-wide program for zero drafts would be pure
        overhead."""
        c = self.config
        t0 = time.perf_counter() if c.profile else None
        wall0 = time.time()
        k = c.spec.num_draft_tokens
        batch = list(self.running)

        # draft first (host-side): capacity needs depend on draft lengths
        draft_by_rid: dict[str, list] = {}
        for r in batch:
            # positions fed this round reach num_tokens-1+L and the pass
            # emits up to L+1 tokens: cap L by the max_tokens budget and
            # the hard max_seq wall (RoPE table)
            cap = min(k, self._remaining(r) - 1,
                      c.model.max_seq - r.num_tokens)
            d = (
                self.drafter.propose(
                    r.request_id, r.prompt_token_ids + r.output_token_ids, cap
                )
                if cap > 0 else []
            )
            draft_by_rid[r.request_id] = list(d)
        t_drafted = time.time()
        if not any(draft_by_rid.values()):
            return self._plain_decode_step()

        # reserve KV for the drafted positions (verify scatters K/V at
        # num_tokens-1 .. num_tokens-1+L); preempt on real pressure only
        while True:
            try:
                for r in self.running:
                    r.seq.ensure_capacity(
                        r.num_tokens + len(draft_by_rid[r.request_id])
                    )
                break
            except NoFreeBlocksError:
                if not self._preempt_one():
                    raise

        batch = list(self.running)
        drafts = [draft_by_rid[r.request_id] for r in batch]
        B = len(batch)
        B_pad = self._pad_to_bucket(B, c.decode_buckets())
        K1 = k + 1
        num_slots = c.num_blocks * c.block_size

        context_lens = np.zeros(B_pad, np.int32)
        draft_tokens = np.zeros((B_pad, k), np.int32)
        draft_lens = np.zeros(B_pad, np.int32)
        bt = np.zeros(
            (B_pad, self._bt_width([len(r.seq.blocks) for r in batch])),
            np.int32,
        )
        for i, r in enumerate(batch):
            d = drafts[i]
            context_lens[i] = r.num_tokens + len(d)
            draft_tokens[i, : len(d)] = d
            draft_lens[i] = len(d)
            bt[i, : len(r.seq.blocks)] = r.seq.blocks

        if c.mixed_batch:
            # ragged verify (ops/ragged via verify_tokens_ragged): pack
            # only the REAL 1 + draft_len tokens per row instead of
            # padding every row to a k+1 trash-slot rectangle — the
            # per-row bucket waste ROADMAP item 1 named. gather_idx
            # recovers the [B, K+1] logits layout accept_draft expects;
            # positions past a row's draft clamp to its last token and
            # are masked by draft_lens, so duplicated logits are never
            # consumed. Acceptance math downstream is unchanged.
            from ray_tpu.llm.mixed import token_bucket

            T_pad = token_bucket(sum(1 + len(d) for d in drafts))
            p_tokens = np.zeros(T_pad, np.int32)
            p_positions = np.zeros(T_pad, np.int32)
            p_slots = np.full(T_pad, num_slots, np.int32)
            p_lora = np.zeros(T_pad, np.int32)  # per-TOKEN adapter slots
            cu = np.zeros(B_pad + 1, np.int32)
            gather = np.zeros((B_pad, K1), np.int32)
            t = 0
            for i, r in enumerate(batch):
                row = [
                    r.output_token_ids[-1] if r.output_token_ids
                    else r.prompt_token_ids[-1]
                ] + drafts[i]
                pos0 = r.num_tokens - 1  # position of the token being fed
                p_tokens[t : t + len(row)] = row
                p_positions[t : t + len(row)] = np.arange(
                    pos0, pos0 + len(row)
                )
                for j in range(len(row)):
                    p_slots[t + j] = r.seq.slot(pos0 + j)
                p_lora[t : t + len(row)] = r.lora_slot
                gather[i] = t + np.minimum(np.arange(K1), len(row) - 1)
                t += len(row)
                cu[i + 1] = t
            cu[B + 1 :] = t  # pad sequences: q_len 0
            logits, self.cache = self._verify_ragged_fn()(
                self.params,
                jnp.asarray(p_tokens),
                jnp.asarray(p_positions),
                jnp.asarray(p_slots),
                jnp.asarray(bt),
                jnp.asarray(cu),
                jnp.asarray(context_lens),
                jnp.asarray(gather),
                self.cache,
                self._lora_arg(p_lora),
            )
        else:
            tokens = np.zeros((B_pad, K1), np.int32)
            positions = np.zeros((B_pad, K1), np.int32)
            slots = np.full((B_pad, K1), num_slots, np.int32)  # trash default
            lora_ids = np.zeros(B_pad, np.int32)
            for i, r in enumerate(batch):
                d = drafts[i]
                last_tok = (
                    r.output_token_ids[-1] if r.output_token_ids
                    else r.prompt_token_ids[-1]
                )
                pos0 = r.num_tokens - 1  # position of the token being fed
                row = [last_tok] + d
                tokens[i, : len(row)] = row
                positions[i, : len(row)] = np.arange(pos0, pos0 + len(row))
                for j in range(len(row)):
                    slots[i, j] = r.seq.slot(pos0 + j)
                lora_ids[i] = r.lora_slot

            logits, self.cache = self._verify_fn(K1)(
                self.params,
                jnp.asarray(tokens),
                jnp.asarray(positions),
                jnp.asarray(slots),
                jnp.asarray(bt),
                jnp.asarray(context_lens),
                self.cache,
                self._lora_arg(lora_ids),
            )

        from ray_tpu.llm.spec.accept import accept_draft

        # acceptance fast paths follow the batch's sampler mode: greedy ->
        # pure argmax comparisons; categorical -> tempered softmax, no
        # full-vocab sort; anything with top-k/top-p -> exact filtering
        batch_mode = self._sample_mode(batch)
        mode = batch_mode if batch_mode in ("greedy", "categorical") else "sample"
        temps = np.array(
            [r.sampling_params.temperature for r in batch] + [1.0] * (B_pad - B),
            np.float32,
        )
        top_ks = np.array(
            [r.sampling_params.top_k for r in batch] + [0] * (B_pad - B), np.int32
        )
        top_ps = np.array(
            [r.sampling_params.top_p for r in batch] + [1.0] * (B_pad - B),
            np.float32,
        )
        keys = [
            jax.random.fold_in(r._key, len(r.output_token_ids)) for r in batch
        ] + [jax.random.key(0)] * (B_pad - B)
        out_toks, out_lps, accepted = accept_draft(
            logits,
            jnp.asarray(draft_tokens),
            jnp.asarray(draft_lens),
            jnp.asarray(temps),
            jnp.asarray(top_ks),
            jnp.asarray(top_ps),
            jnp.stack(keys),
            mode=mode,
        )
        out_toks = np.asarray(out_toks)   # host sync
        out_lps = np.asarray(out_lps)
        accepted = np.asarray(accepted)
        t_verified = time.time()

        # keep accepted+1 tokens per row, run the usual stop ladder
        counts = (accepted[:B] + 1).tolist()
        outputs = self._append_chunk(
            batch, out_toks[:B].T, out_lps[:B].T, row_counts=counts
        )

        # KV rollback: blocks reserved for rejected draft positions are
        # returned; the stale K/V device-side is masked by context_lens
        # and rewritten when a real token reaches that position
        for r in batch:
            if r.status == RequestStatus.RUNNING and r.seq is not None:
                r.seq.truncate_to(r.num_tokens)

        # stats + observability
        st = self.spec_stats
        n_drafted = int(draft_lens[:B].sum())
        n_accepted = int(accepted[:B].sum())
        n_emitted = sum(len(o.new_token_ids) for o in outputs)
        st.steps += 1
        st.rows += B
        st.drafted += n_drafted
        st.accepted += n_accepted
        st.emitted += n_emitted
        from ray_tpu.llm.spec.stats import export_spec_stats, record_spec_chunk

        export_spec_stats(st, n_drafted, n_accepted, n_emitted)
        if t0 is not None:
            record_spec_chunk(
                1e3 * (time.perf_counter() - t0), k, n_accepted, B
            )
        draft_ms = round((t_drafted - wall0) * 1e3, 3)
        verify_ms = round((t_verified - t_drafted) * 1e3, 3)
        extra = {
            r.request_id: {
                "k": k,
                "drafted": int(draft_lens[i]),
                "accepted": int(accepted[i]),
                "draft_ms": draft_ms,
                "verify_ms": verify_ms,
            }
            for i, r in enumerate(batch)
        }
        return self._obs_decode_round(
            batch, outputs, wall0, "engine.spec_round", k, extra=extra
        )

    def _plain_decode_step(self) -> list[RequestOutput]:
        c = self.config
        t0 = time.perf_counter() if c.profile else None
        wall0 = time.time()
        n_steps = self._chunk_steps()
        # grow each sequence by the chunk's slots it can actually USE —
        # overshoot steps past a request's max_tokens write the trash page
        # in-graph (decode_loop `remaining`), so reserving full-chunk KV
        # for a request that finishes next token would preempt a peer to
        # fund blocks nobody reads. Preempt on real cache pressure only.
        while True:
            try:
                for r in self.running:
                    r.seq.ensure_capacity(
                        r.num_tokens + min(n_steps, self._remaining(r))
                    )
                break
            except NoFreeBlocksError:
                if not self._preempt_one():
                    raise  # single running request can't fit: cache too small
        batch = list(self.running)
        B = len(batch)
        B_pad = self._pad_to_bucket(B, c.decode_buckets())
        num_slots = c.num_blocks * c.block_size

        # per-row assembly shared with the pipelined DeviceBatchState
        # (pipeline.assemble_batch_arrays): one source of truth for how
        # a Request becomes batch rows — the bitwise-identity contract
        # between the two paths depends on it
        from ray_tpu.llm.pipeline import assemble_batch_arrays

        a, keys = assemble_batch_arrays(
            batch, B_pad, self._bt_width([len(r.seq.blocks) for r in batch])
        )
        tokens, positions = a["tokens"], a["positions"]
        context_lens, lora_ids, bt = a["context_lens"], a["lora_ids"], a["bt"]

        if n_steps == 1:
            slot_mapping = np.full(B_pad, num_slots, np.int32)
            for i, r in enumerate(batch):
                slot_mapping[i] = r.seq.slot(int(positions[i]))
            logits, self.cache = self._decode(
                self.params,
                jnp.asarray(tokens),
                jnp.asarray(positions),
                jnp.asarray(slot_mapping),
                jnp.asarray(bt),
                jnp.asarray(context_lens),
                self.cache,
                self._lora_arg(lora_ids),
            )
            tok, logprob = self._sample_batch(logits[:B], batch)
            if t0 is not None:
                from ray_tpu.llm.decode_loop import record_chunk

                record_chunk(
                    1e3 * (time.perf_counter() - t0), 1,
                    self._sample_mode(batch), B,
                )
            return self._obs_decode_round(
                batch, self._append_tokens(batch, tok, logprob), wall0,
                "engine.decode_chunk", 1,
            )

        # multi-step chunk: decode+sample n_steps times on device, one
        # sync. keys derive from (stable request key, absolute output
        # index — a["starts"]): identical sampling regardless of how
        # co-running requests partition the chunks. remaining = this
        # chunk's keep-capacity (writes past it hit the trash page)
        remaining = np.zeros(B_pad, np.int32)
        for i, r in enumerate(batch):
            remaining[i] = self._remaining(r)
        toks, logprobs, self.cache = self._decode_chunk_fn(
            n_steps, self._sample_mode(batch)
        )(
            self.params,
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray(bt),
            jnp.asarray(context_lens),
            self.cache,
            jnp.asarray(a["temps"]),
            jnp.asarray(a["top_ks"]),
            jnp.asarray(a["top_ps"]),
            jnp.stack(keys),
            jnp.asarray(a["starts"]),
            jnp.asarray(remaining),
            self._lora_arg(lora_ids),
        )
        toks_np, logprobs_np = np.asarray(toks), np.asarray(logprobs)
        if t0 is not None:
            from ray_tpu.llm.decode_loop import record_chunk

            # np.asarray is the host sync: this is the full round trip
            record_chunk(
                1e3 * (time.perf_counter() - t0), n_steps,
                self._sample_mode(batch), B,
            )
        return self._obs_decode_round(
            batch, self._append_chunk(batch, toks_np, logprobs_np), wall0,
            "engine.decode_chunk", n_steps,
        )

    # -- sampling + bookkeeping ----------------------------------------------

    def _sample_batch(self, logits, batch: list) -> tuple[np.ndarray, np.ndarray]:
        B = len(batch)
        temps = np.array([r.sampling_params.temperature for r in batch], np.float32)
        top_ks = np.array([r.sampling_params.top_k for r in batch], np.int32)
        top_ps = np.array([r.sampling_params.top_p for r in batch], np.float32)
        # key = fold(stable request key, absolute output index): the same
        # request samples the same stream whether it decodes token-by-token
        # or in chunks, under any co-running load (see _decode_step)
        keys = [
            jax.random.fold_in(r._key, len(r.output_token_ids)) for r in batch
        ]
        toks, logprobs = sample_tokens(
            logits[:B],
            jnp.asarray(temps),
            jnp.asarray(top_ks),
            jnp.asarray(top_ps),
            jnp.stack(keys),
            mode=self._sample_mode(batch),
        )
        return np.asarray(toks), np.asarray(logprobs)

    def _append_chunk(self, batch: list, toks, logprobs,
                      row_counts: Optional[list] = None) -> list[RequestOutput]:
        """Host bookkeeping after a device-side chunk: walk each request's
        token column in order, keep until a stop condition fires, discard
        the overshoot (its KV sits in the request's own unsealed blocks,
        released with the sequence). One RequestOutput per request.
        ``row_counts`` caps the walk per row (speculative decoding: row i
        emitted accepted_i + 1 tokens, the rest of its column is pad)."""
        c = self.config
        outputs = []
        n = toks.shape[0]
        for i, r in enumerate(batch):
            sp = r.sampling_params
            new_toks: list[int] = []
            finished = False
            for s in range(n if row_counts is None else min(n, row_counts[i])):
                t = int(toks[s, i])
                lp = float(logprobs[s, i])
                new_toks.append(t)
                r.output_token_ids.append(t)
                r.cumulative_logprob += lp
                if sp.logprobs:
                    r.token_logprobs.append(lp)
                if not sp.ignore_eos and t == c.eos_token_id:
                    finished, r.finish_reason = True, "stop"
                elif t in sp.stop_token_ids:
                    finished, r.finish_reason = True, "stop"
                elif len(r.output_token_ids) >= sp.max_tokens:
                    finished, r.finish_reason = True, "length"
                elif r.num_tokens >= c.model.max_seq:
                    finished, r.finish_reason = True, "length"
                if finished:
                    break
            num_cached = r.seq.num_cached_tokens if r.seq else 0
            written = r.prompt_token_ids + r.output_token_ids[:-1]
            if finished:
                r.status = RequestStatus.FINISHED
                self.running.remove(r)
                if c.enable_prefix_caching:
                    r.seq.seal_full_blocks(written)
                r.seq.release()
                self.requests.pop(r.request_id, None)
                if self.drafter is not None:
                    self.drafter.release(r.request_id)
            else:
                if c.enable_prefix_caching:
                    # seals only blocks fully covered by `written`; a
                    # mid-chunk boundary crossing is caught here too
                    r.seq.seal_full_blocks(written)
                r.seq.num_tokens = r.num_tokens
            outputs.append(
                RequestOutput(
                    request_id=r.request_id,
                    new_token_ids=new_toks,
                    output_token_ids=list(r.output_token_ids),
                    finished=finished,
                    finish_reason=r.finish_reason,
                    num_cached_tokens=num_cached,
                )
            )
        return outputs

    def _append_tokens(self, batch: list, toks, logprobs) -> list[RequestOutput]:
        """Single-step bookkeeping: the n=1 case of _append_chunk (ONE
        stop-condition/seal/release ladder, not two copies that drift)."""
        return self._append_chunk(
            batch, np.asarray(toks)[None, :], np.asarray(logprobs)[None, :]
        )
