"""Admission control for the OpenAI serving app: shed load before the
queue does it for you.

Reference analogs: vLLM's engine backpressure + Serve's
``max_queued_requests`` 503s (python/ray/serve/_private/proxy.py) —
specialized here with the r08 observability loop closed: the
``llm_queue_wait_seconds`` histogram that ``ray_tpu.obs.slo`` records
per finished request *prices* both the shedding decision and the
``Retry-After`` hint. Two triggers:

 * queue depth: more than ``max_queue_depth`` requests already waiting
   in the engine → 429 (the engine would only ever park the new arrival
   behind them);
 * measured queue-wait SLO: the recent mean queue_wait (windowed delta
   over the histogram) exceeds ``target_queue_wait_s`` while the queue
   is non-trivially deep → 429 even below the depth cap, because the
   SLO is already burning.

Draining (SIGTERM / maintenance) turns every new request into a 503
with ``Retry-After`` while in-flight requests finish. Rejections are
counted in ``llm_admission_rejected_total{model,code,tenant}`` (tenant
empty outside a multi-tenant fleet).
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Optional

from ray_tpu.util.metrics import Counter


@dataclasses.dataclass
class AdmissionConfig:
    # waiting-queue depth at which new requests shed (-1 = unbounded)
    max_queue_depth: int = -1
    # recent mean queue_wait above this sheds (0 = SLO trigger disabled)
    target_queue_wait_s: float = 0.0
    # SLO shedding needs this much queue to act on (a briefly-slow lone
    # request must not flip the app into rejecting everything)
    min_queue_depth: int = 2
    # histogram delta window for "recent" queue_wait
    window_s: float = 10.0
    retry_after_floor_s: float = 0.1
    retry_after_cap_s: float = 30.0
    drain_retry_after_s: float = 5.0

    def __post_init__(self):
        if self.retry_after_cap_s < self.retry_after_floor_s:
            raise ValueError("retry_after_cap_s < retry_after_floor_s")


def rejected_counter() -> Counter:
    return Counter(
        "llm_admission_rejected_total",
        description="serving admission control: requests shed with 429 "
        "(overload) or 503 (draining), attributable per tenant (empty "
        "tenant = pre-fleet single-tenant serving)",
        tag_keys=("model", "code", "tenant"),
    )


def register_metrics() -> None:
    """scripts/check_metrics.py hook."""
    rejected_counter()


class AdmissionController:
    """Per-LLMServer admission decisions; thread-safe, observability-fed."""

    def __init__(self, config: Optional[AdmissionConfig] = None,
                 model_tag: str = "engine", tenant: str = ""):
        from collections import deque

        self.config = config or AdmissionConfig()
        self.model_tag = model_tag
        # fleet QoS: one controller per tenant labels its shed counters;
        # the pre-fleet single-tenant server leaves this empty
        self.tenant = tenant
        self.draining = False
        self._lock = threading.Lock()
        # (t, cum_sum, cum_count) snapshots of the queue_wait histogram,
        # kept just long enough to window a delta over window_s. The
        # computed mean is TTL-cached so the histogram walk + snapshot
        # churn run a few times per second REGARDLESS of request rate —
        # admission cost must not grow with the very load it sheds
        self._snaps: "deque[tuple[float, float, int]]" = deque()
        self._cached_mean: tuple[float, Optional[float]] = (0.0, None)
        self.num_rejected_429 = 0
        self.num_rejected_503 = 0

    MEAN_CACHE_TTL_S = 0.25

    # -- drain ----------------------------------------------------------------

    def start_drain(self) -> None:
        self.draining = True

    # -- the observability loop: queue_wait priced from the SLO histogram -----

    def _queue_wait_cum(self) -> tuple[float, int]:
        """Cumulative (sum_s, count) of llm_queue_wait_seconds for this
        model across the process registry."""
        try:
            from ray_tpu.obs import slo

            data = slo.queue_wait_histogram().hist_data()
        except Exception:  # noqa: BLE001 — metrics must not break admission
            return (0.0, 0)
        total, count = 0.0, 0
        for key, (_buckets, s, n) in data.items():
            if key and key[0] == self.model_tag:
                total += s
                count += n
        return (total, count)

    def recent_queue_wait_mean(self) -> Optional[float]:
        """Mean queue_wait over roughly the last window_s, from histogram
        snapshot deltas (TTL-cached); None until a request landed."""
        now = time.monotonic()
        with self._lock:
            t_cache, cached = self._cached_mean
            if now - t_cache < self.MEAN_CACHE_TTL_S:
                return cached
        cum_sum, cum_count = self._queue_wait_cum()
        with self._lock:
            self._snaps.append((now, cum_sum, cum_count))
            horizon = now - self.config.window_s
            # keep ONE snapshot at/behind the horizon as the delta base
            while len(self._snaps) >= 2 and self._snaps[1][0] <= horizon:
                self._snaps.popleft()
            _t0, s0, n0 = self._snaps[0]
            if cum_count > n0:
                mean: Optional[float] = (cum_sum - s0) / (cum_count - n0)
            elif cum_count > 0:
                # nothing finished inside the window: lifetime fallback
                mean = cum_sum / cum_count
            else:
                mean = None
            self._cached_mean = (now, mean)
        return mean

    def estimate_retry_after(self, num_waiting: int, num_running: int) -> float:
        """Price the hint from measured behavior: the queue ahead of a
        retry is ~num_waiting deep and drains at ~mean queue_wait per
        admission wave (scaled by how loaded decode is)."""
        cfg = self.config
        per = self.recent_queue_wait_mean()
        if per is None or per <= 0:
            per = cfg.target_queue_wait_s or 0.5
        est = per * (1.0 + num_waiting / max(1, num_running))
        return min(cfg.retry_after_cap_s, max(cfg.retry_after_floor_s, est))

    # -- the decision ---------------------------------------------------------

    def check(self, *, num_waiting: int, num_running: int) -> Optional[dict]:
        """None = admit; otherwise an OpenAI-style error payload carrying
        ``code`` (429/503) and ``retry_after`` seconds (the HTTP proxy
        maps these onto the status line and Retry-After header)."""
        cfg = self.config
        if self.draining:
            with self._lock:
                self.num_rejected_503 += 1
            self._count("503")
            return self._payload(
                503, "service_unavailable_error",
                "server is draining; retry against another replica",
                cfg.drain_retry_after_s,
            )
        reason = None
        # num_waiting > 0 guard: depth 0 means "no waiting queue", not
        # "reject even when idle" — an idle engine always admits
        if (cfg.max_queue_depth >= 0 and num_waiting > 0
                and num_waiting >= cfg.max_queue_depth):
            reason = (
                f"queue depth {num_waiting} >= max_queue_depth="
                f"{cfg.max_queue_depth}"
            )
        elif cfg.target_queue_wait_s > 0 and num_waiting >= cfg.min_queue_depth:
            mean = self.recent_queue_wait_mean()
            if mean is not None and mean > cfg.target_queue_wait_s:
                reason = (
                    f"recent mean queue_wait {mean:.3f}s > SLO "
                    f"{cfg.target_queue_wait_s}s at depth {num_waiting}"
                )
        if reason is None:
            return None
        with self._lock:
            self.num_rejected_429 += 1
        self._count("429")
        return self._payload(
            429, "rate_limit_error", f"overloaded: {reason}",
            self.estimate_retry_after(num_waiting, num_running),
        )

    def _payload(self, code: int, err_type: str, message: str,
                 retry_after: float) -> dict:
        return {
            "error": {
                "message": message,
                "type": err_type,
                "code": code,
                "retry_after": round(float(retry_after), 3),
            }
        }

    def _count(self, code: str) -> None:
        try:
            rejected_counter().inc(
                tags={"model": self.model_tag, "code": code,
                      "tenant": self.tenant}
            )
        except Exception:  # noqa: BLE001
            pass

    def stats(self) -> dict:
        return {
            "draining": self.draining,
            "rejected_429": self.num_rejected_429,
            "rejected_503": self.num_rejected_503,
            "recent_queue_wait_mean_s": self.recent_queue_wait_mean(),
        }


def retry_after_header(payload: dict) -> Optional[str]:
    """Retry-After header value for a rejection payload (whole seconds,
    rounded up — RFC 7231 delta-seconds)."""
    err = payload.get("error") if isinstance(payload, dict) else None
    if not isinstance(err, dict):
        return None
    ra = err.get("retry_after")
    if ra is None:
        return None
    return str(int(math.ceil(float(ra))))
