"""Acceptance-rate accounting + observability export.

Three surfaces, mirroring the decode-chunk profiling hooks
(llm/decode_loop.py):

 * SpecStats — host counters the engine folds into ``stats()``;
 * Prometheus — counters/gauges on the dashboard /metrics route
   (util/metrics.py process-wide registry);
 * timeline — per-verify-chunk spans (kind="profile") next to task
   spans on the dashboard /timeline route, when EngineConfig.profile.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class SpecStats:
    """Host-side running totals for one engine."""

    steps: int = 0       # verification passes dispatched
    rows: int = 0        # sequence-rows verified (sum of batch sizes)
    drafted: int = 0     # draft tokens proposed
    accepted: int = 0    # draft tokens accepted
    emitted: int = 0     # tokens actually kept (accepted + bonus, post-stop)

    @property
    def acceptance_rate(self) -> float:
        """Accepted / drafted — drafter quality (1.0 = every guess right)."""
        return self.accepted / self.drafted if self.drafted else 0.0

    @property
    def mean_accepted_len(self) -> float:
        """Tokens emitted per row per verify pass (incl. the bonus token):
        the speedup lever — n bandwidth-bound decode steps collapse into
        one verify pass when this is n."""
        return self.emitted / self.rows if self.rows else 0.0

    def to_dict(self) -> dict:
        return {
            "steps": self.steps,
            "rows": self.rows,
            "drafted_tokens": self.drafted,
            "accepted_tokens": self.accepted,
            "emitted_tokens": self.emitted,
            "acceptance_rate": round(self.acceptance_rate, 4),
            "mean_accepted_len": round(self.mean_accepted_len, 4),
        }


_metrics = None


def _spec_metrics():
    """Lazy singletons (same-name re-registration shares storage, but the
    first construction still takes the registry lock — keep it off the
    per-chunk path)."""
    global _metrics
    if _metrics is None:
        from ray_tpu.obs.telemetry import AGG_MAX, declare_aggregation
        from ray_tpu.util.metrics import Counter, Gauge

        # cluster-telemetry aggregation: the fleet-level acceptance rate
        # derives from the drafted/accepted counter SUMS; the gauges are
        # per-engine running rates, where max is the honest rollup
        # (averaging rates across unevenly-loaded engines lies)
        declare_aggregation("llm_spec_acceptance_rate", AGG_MAX)
        declare_aggregation("llm_spec_mean_accepted_len", AGG_MAX)
        _metrics = {
            "drafted": Counter(
                "llm_spec_drafted_tokens_total",
                description="speculative decoding: draft tokens proposed",
            ),
            "accepted": Counter(
                "llm_spec_accepted_tokens_total",
                description="speculative decoding: draft tokens accepted",
            ),
            "emitted": Counter(
                "llm_spec_emitted_tokens_total",
                description="speculative decoding: tokens emitted by verify "
                "passes (accepted + bonus, after stop conditions)",
            ),
            "acceptance_rate": Gauge(
                "llm_spec_acceptance_rate",
                description="speculative decoding: running accepted/drafted",
            ),
            "mean_accepted_len": Gauge(
                "llm_spec_mean_accepted_len",
                description="speculative decoding: running emitted tokens per "
                "verified row (includes the bonus token)",
            ),
        }
    return _metrics


def export_spec_stats(stats: SpecStats, drafted: int, accepted: int,
                      emitted: int) -> None:
    """Publish one verify pass's deltas + the running rates. Observability
    must not break decode: failures are swallowed."""
    try:
        m = _spec_metrics()
        if drafted:
            m["drafted"].inc(drafted)
        if accepted:
            m["accepted"].inc(accepted)
        if emitted:
            m["emitted"].inc(emitted)
        m["acceptance_rate"].set(stats.acceptance_rate)
        m["mean_accepted_len"].set(stats.mean_accepted_len)
    except Exception:  # noqa: BLE001 — observability must not break decode
        pass


def record_spec_chunk(ms: float, k: int, accepted: int, batch_size: int) -> None:
    """Timeline span + latency histogram for one draft->verify->accept
    round trip (EngineConfig.profile path — the spec analog of
    decode_loop.record_chunk)."""
    try:
        import time

        from ray_tpu.util.metrics import Histogram

        Histogram(
            "llm_spec_chunk_ms",
            description="profiler: wall ms per speculative verify chunk "
            "(draft + verify + accept + rollback + host sync)",
            boundaries=[0.5, 1, 2, 5, 10, 25, 50, 100, 250, 500, 1000, 5000],
            tag_keys=("k",),
        ).observe(ms, tags={"k": str(k)})

        from ray_tpu.core import runtime as rt
        from ray_tpu.core.events import TaskState

        buf = rt.get_runtime().task_events
        end = time.time()
        span = f"profile-spec-chunk-{time.monotonic_ns()}"
        name = f"profile:spec_chunk:{k}x{batch_size}:acc{accepted}"
        buf.record(span, name, TaskState.RUNNING, kind="profile",
                   worker="llm-engine", ts=end - ms / 1e3)
        buf.record(span, name, TaskState.FINISHED, kind="profile",
                   worker="llm-engine", ts=end)
    except Exception:  # noqa: BLE001 — observability must not break decode
        pass
