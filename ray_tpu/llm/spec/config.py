"""SpecConfig: the EngineConfig.spec knob block.

Reference shape: vLLM's SpeculativeConfig (method="ngram" vs a draft
model id). Validation happens at engine construction, not inside the
decode hot path — a bad knob must fail loudly at startup.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from ray_tpu.llm.kv_cache import KVCacheConfig


@dataclasses.dataclass
class SpecConfig:
    # k: drafted tokens per verification pass. COMPILE-TIME bucket: the
    # verifier always runs a [B_pad, k+1]-shaped program (rows with
    # shorter/empty drafts pad with trash-slot columns), so one value of
    # k means one compiled verify program per decode-batch bucket.
    num_draft_tokens: int = 4
    method: str = "prompt_lookup"  # "prompt_lookup" | "draft_model"

    # prompt-lookup drafting: longest suffix n-gram of the request's
    # (prompt + generated) history that occurred earlier; propose the
    # tokens that followed. Model-free — wins on repetitive/extractive
    # workloads (code edits, RAG quoting, summarization).
    max_ngram: int = 3
    min_ngram: int = 1
    max_history: int = 4096  # lookup window (host-side cost cap)

    # draft-model drafting: a smaller llama run through the same
    # models/llama_decode paths with its OWN paged KV cache (draft_kv
    # sizes it; head/layer dims always follow the draft model config).
    draft_model: Any = None          # LlamaConfig or registry name
    draft_params: Any = None         # weights pytree; random-init if None
    draft_kv: Optional[KVCacheConfig] = None
    draft_seed: int = 0

    def __post_init__(self):
        if self.num_draft_tokens < 1:
            raise ValueError(
                f"num_draft_tokens must be >= 1, got {self.num_draft_tokens}"
            )
        if self.method not in ("prompt_lookup", "draft_model"):
            raise ValueError(
                f"spec method must be 'prompt_lookup' or 'draft_model', "
                f"got {self.method!r}"
            )
        if not (1 <= self.min_ngram <= self.max_ngram):
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"{self.min_ngram}/{self.max_ngram}"
            )
        if isinstance(self.draft_model, str):
            from ray_tpu.models.registry import get_model_config

            self.draft_model = get_model_config(self.draft_model)
        if self.method == "draft_model" and self.draft_model is None:
            raise ValueError("method='draft_model' requires draft_model")

    def build_drafter(self, target_config) -> "Any":
        """Construct the drafter for an engine serving `target_config`."""
        from ray_tpu.llm.spec.drafter import (
            DraftModelDrafter,
            PromptLookupDrafter,
        )

        if self.method == "prompt_lookup":
            return PromptLookupDrafter(
                max_ngram=self.max_ngram,
                min_ngram=self.min_ngram,
                max_history=self.max_history,
            )
        if self.draft_model.vocab_size != target_config.vocab_size:
            # drafted ids are fed straight to the target verifier
            raise ValueError(
                f"draft model vocab {self.draft_model.vocab_size} != target "
                f"vocab {target_config.vocab_size}"
            )
        return DraftModelDrafter(
            self.draft_model,
            params=self.draft_params,
            kv=self.draft_kv,
            seed=self.draft_seed,
        )
