"""ray_tpu.llm.spec — speculative decoding for the paged-KV engine.

The r06 roofline profile puts decode firmly bandwidth-bound: every
generated token streams the whole model from HBM to produce one row of
logits. Speculative decoding converts k of those bandwidth-bound steps
into ONE compute-dense verification pass (models/llama_decode.
verify_tokens — the prefill path over a k+1-token suffix), so the
weights are read once per k+1 tokens instead of once per token, at
unchanged output distribution.

Pieces:

 * drafter.py  — proposal sources: a model-free prompt-lookup/n-gram
   drafter over the request's token history, and a small-draft-model
   drafter reusing models/llama_decode with its own KV cache;
 * accept.py   — distribution-preserving acceptance/rejection sampling
   (greedy short-circuit when the whole batch is greedy) + the
   resample-on-reject bonus token;
 * config.py   — SpecConfig (EngineConfig.spec), drafter construction;
 * stats.py    — acceptance-rate accounting -> engine.stats(),
   Prometheus counters/gauges, dashboard timeline spans.

KV bookkeeping: drafted K/V lands in the sequence's own unsealed blocks;
rejected positions are rolled back host-side with
SequenceBlocks.truncate_to (kv_cache.py) — device-side the stale slots
are simply masked by context_lens and overwritten by the next real
token at that position.
"""

from ray_tpu.llm.spec.accept import accept_draft
from ray_tpu.llm.spec.config import SpecConfig
from ray_tpu.llm.spec.drafter import (
    Drafter,
    DraftModelDrafter,
    PromptLookupDrafter,
)
from ray_tpu.llm.spec.stats import SpecStats, record_spec_chunk

__all__ = [
    "Drafter",
    "DraftModelDrafter",
    "PromptLookupDrafter",
    "SpecConfig",
    "SpecStats",
    "accept_draft",
    "record_spec_chunk",
]
