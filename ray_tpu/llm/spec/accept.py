"""Distribution-preserving acceptance/rejection for drafted tokens.

Standard speculative-sampling rule (Leviathan et al. / Chen et al.)
specialized to DETERMINISTIC drafters (both of ours propose point
distributions): accept drafted token x_j with probability
p_j(x_j) — the target probability of the drafted token — and on the
first rejection resample from the residual max(p_j - onehot(x_j), 0)
renormalized. If every draft survives, a BONUS token is sampled from
p_k (the logits position after the last drafted token), so a verify
pass always emits accepted + 1 tokens: the k=0 row degenerates to a
plain decode step. The marginal distribution of every emitted token is
exactly the target sampling distribution.

Target distributions come from sampling.target_probs — temperature +
top-k/top-p applied EXACTLY over the full vocab (no TOP_CAP clamp: the
sort is paid once per k tokens, so exactness is affordable here).

Greedy short-circuit (`mode="greedy"`): accept iff the target argmax
equals the drafted token; the rejection resample and the bonus token
are both the position's argmax, so spec output is token-identical to
plain greedy decode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ray_tpu.llm.sampling import target_probs


@functools.partial(jax.jit, static_argnames=("mode",))
def accept_draft(
    logits: jax.Array,        # [B, K+1, V] fp32 target logits; position j
                              # conditions on fed tokens 0..j
    draft_tokens: jax.Array,  # [B, K] int32 (pad arbitrary past draft_lens)
    draft_lens: jax.Array,    # [B] int32, 0..K
    temperatures: jax.Array,  # [B]
    top_ks: jax.Array,        # [B]
    top_ps: jax.Array,        # [B]
    keys: jax.Array,          # [B] PRNG keys (unused in greedy mode)
    mode: str = "sample",     # static: "greedy" | "categorical" | "sample"
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (out_tokens [B, K+1], out_logprobs [B, K+1], accepted [B]).

    Row semantics: columns 0..accepted-1 are the accepted drafted tokens,
    column `accepted` is the bonus/resample token — the caller keeps
    accepted + 1 tokens per row and ignores the rest. Logprobs are
    log-softmax of the raw logits at the emitted token (the same
    convention sample_tokens uses).
    """
    B, K1, V = logits.shape
    K = K1 - 1
    assert K >= 1, "spec verify needs at least one drafted column"
    jpos = jnp.arange(K)[None, :]
    cols = jnp.arange(K1)[None, :]
    logp_all = jax.nn.log_softmax(logits, axis=-1)  # [B, K+1, V]

    if mode == "greedy":
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K+1]
        ok = (greedy[:, :K] == draft_tokens) & (jpos < draft_lens[:, None])
        accepted = jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(axis=1)
        # accepted cols equal the draft (== argmax there); the col at
        # `accepted` is the bonus (all accepted) or the corrected argmax
        # (rejection residual's argmax == argmax, since the rejected
        # draft token was by definition not the argmax)
        out = greedy
        lp = jnp.take_along_axis(logp_all, out[..., None], axis=-1)[..., 0]
        return out, lp, accepted

    # per-position target distributions with per-row knobs [B, K+1, V].
    # STATIC fast path mirroring the engine's _sample_mode: a batch with
    # no top-k/top-p active among its sampled rows ("categorical") needs
    # no full-vocab sort — plain tempered softmax is the exact target
    if mode == "categorical":
        t = jnp.where(temperatures <= 0.0, 1.0, temperatures)[:, None, None]
        p = jax.nn.softmax(logits / t, axis=-1)
    else:
        p = jax.vmap(
            lambda lg: target_probs(lg, temperatures, top_ks, top_ps),
            in_axes=1, out_axes=1,
        )(logits)

    p_draft = jnp.take_along_axis(
        p[:, :K], draft_tokens[..., None], axis=-1
    )[..., 0]  # [B, K]
    ukeys = jax.vmap(jax.random.fold_in)(keys, jnp.zeros((B,), jnp.int32))
    u = jax.vmap(lambda k_: jax.random.uniform(k_, (K,)))(ukeys)
    # per-row greedy short-circuit (mirrors sample_tokens): a greedy row
    # in a mixed batch accepts iff the draft IS the argmax, and emits
    # argmax at the rejection/bonus position — its temperature was
    # remapped to 1.0 above only to keep the math NaN-free, so without
    # this mask it would silently receive temp-1.0 samples
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K+1]
    is_greedy = temperatures <= 0.0  # [B]
    ok = jnp.where(
        is_greedy[:, None], greedy_tok[:, :K] == draft_tokens, u < p_draft
    ) & (jpos < draft_lens[:, None])
    accepted = jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(axis=1)  # [B]

    # distribution at the emit position: bonus (ran out of drafts —
    # sample the target directly) or residual (first rejection)
    p_a = jnp.take_along_axis(p, accepted[:, None, None], axis=1)[:, 0]  # [B, V]
    d_a = jnp.take_along_axis(
        draft_tokens, jnp.clip(accepted, 0, K - 1)[:, None], axis=1
    )[:, 0]
    resid = jnp.maximum(p_a - jax.nn.one_hot(d_a, V, dtype=p_a.dtype), 0.0)
    rs = resid.sum(axis=-1, keepdims=True)
    # an all-zero residual means p_a was entirely on the drafted token,
    # which is then accepted with probability 1 — unreachable, but the
    # fallback keeps the kernel NaN-free
    resid = jnp.where(rs > 0.0, resid / jnp.maximum(rs, 1e-20), p_a)
    rejected = accepted < draft_lens
    final_dist = jnp.where(rejected[:, None], resid, p_a)
    bkeys = jax.vmap(jax.random.fold_in)(keys, jnp.ones((B,), jnp.int32))
    final_tok = jax.vmap(jax.random.categorical)(
        bkeys, jnp.log(jnp.maximum(final_dist, 1e-38))
    ).astype(jnp.int32)
    # greedy rows: bonus = argmax; rejection resample = argmax too (the
    # rejected draft was by definition not the argmax)
    final_tok = jnp.where(
        is_greedy,
        jnp.take_along_axis(greedy_tok, accepted[:, None], axis=1)[:, 0],
        final_tok,
    )

    draft_pad = jnp.pad(draft_tokens, ((0, 0), (0, 1)))  # [B, K+1]
    out = jnp.where(cols < accepted[:, None], draft_pad, 0)
    out = jnp.where(cols == accepted[:, None], final_tok[:, None], out)
    lp = jnp.take_along_axis(logp_all, out[..., None], axis=-1)[..., 0]
    return out.astype(jnp.int32), lp, accepted
