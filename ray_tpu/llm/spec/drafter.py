"""Draft-token proposal sources for speculative decoding.

Both drafters are DETERMINISTIC (a proposal is a point distribution),
which keeps the acceptance math simple: accept token x with probability
p_target(x), resample-on-reject from the residual (accept.py). That is
the same modeling choice vLLM makes for its ngram proposer.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.llm.kv_cache import (
    BlockAllocator,
    KVCacheConfig,
    NoFreeBlocksError,
    SequenceBlocks,
)


class Drafter:
    """Interface: propose up to k continuation tokens for a request.

    ``tokens`` is the request's full visible history (prompt + generated)
    — every token the next real decode step would condition on.
    ``release`` drops any per-request state (finish/abort/preempt)."""

    def propose(self, request_id: str, tokens: list, k: int) -> list:
        raise NotImplementedError

    def release(self, request_id: str) -> None:  # stateless by default
        return None


class PromptLookupDrafter(Drafter):
    """Model-free prompt-lookup (n-gram) drafting.

    Find the longest suffix n-gram (max_ngram down to min_ngram) of the
    history that occurred earlier, and propose the k tokens that
    followed its MOST RECENT earlier occurrence. Zero device work: wins
    whenever generation quotes its own context (retrieval answers, code
    edits, repetitive structure) and costs only a bounded host scan when
    it misses — exactly the regime where a draft model's extra HBM
    traffic is hardest to justify.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1,
                 max_history: int = 4096):
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self.max_history = max_history

    def propose(self, request_id: str, tokens: list, k: int) -> list:
        toks = tokens[-self.max_history:]
        n_tok = len(toks)
        if n_tok < 2:
            return []
        # vectorized scan: this runs per row per decode round on the
        # decode critical path, so the window match is numpy over an int
        # array, not a Python list-slice loop (miss cost at the default
        # 4096-token window was milliseconds per round, serialized
        # before the verify dispatch)
        arr = np.asarray(toks, dtype=np.int64)
        for n in range(min(self.max_ngram, n_tok - 1), self.min_ngram - 1, -1):
            pat = arr[n_tok - n:]
            # windows over arr[:-1]: starts 0..n_tok-n-1, i.e. every
            # occurrence strictly before the suffix itself (overlap with
            # the suffix is fine — that is exactly a short cycle)
            wins = np.lib.stride_tricks.sliding_window_view(arr[:-1], n)
            hits = np.flatnonzero((wins == pat).all(axis=1))
            if hits.size:
                # most recent earlier occurrence: recency beats frequency
                # for continuation quality (the cycle being generated NOW
                # matters more than one from 1000 tokens ago)
                i = int(hits[-1])
                return [int(t) for t in toks[i + n : i + n + k]]
        return []


class DraftModelDrafter(Drafter):
    """Greedy drafting with a smaller model over its OWN paged KV cache.

    Reuses models/llama_decode end to end (prefill to ingest history
    deltas, decode_step to extend greedily), with a private
    BlockAllocator/SequenceBlocks per request sized by a KVCacheConfig.
    Sync with the target engine is by longest-common-prefix: accepted
    draft tokens are already in the draft cache; a rejected/resampled
    token shows up as a history mismatch and rolls the draft sequence
    back with the same truncate_to the engine uses.
    """

    def __init__(
        self,
        model_config,
        params=None,
        *,
        kv: Optional[KVCacheConfig] = None,
        seed: int = 0,
    ):
        import jax

        from ray_tpu.models import llama
        from ray_tpu.models.llama_decode import decode_step, init_cache, prefill

        c = model_config
        self.config = c
        self.params = (
            params if params is not None
            else llama.init_params(c, jax.random.key(seed))
        )
        kv = kv or KVCacheConfig(num_blocks=256, block_size=16)
        # head/layer dims always follow the draft model; only capacity
        # knobs (num_blocks/block_size/dtype) come from the caller's kv
        self.kv = KVCacheConfig(
            num_blocks=kv.num_blocks, block_size=kv.block_size,
            n_layers=c.n_layers, n_kv_heads=c.n_kv_heads,
            head_dim=c.head_dim, dtype=kv.dtype,
        )
        self.allocator = BlockAllocator(self.kv.num_blocks, self.kv.block_size)
        self.cache = init_cache(
            c, self.kv.num_slots, dtype=self.kv.dtype,
            trash_slots=self.kv.block_size,
        )
        self._states: dict[str, dict] = {}  # rid -> {"seq", "hist"}
        bs = self.kv.block_size
        self._prefill = jax.jit(
            lambda params, t, p, sl, sm, bt, cl, cache: prefill(
                params, t, p, sl, sm, bt, cl, cache, c, block_size=bs,
            ),
            donate_argnums=(7,),
        )
        self._decode = jax.jit(
            lambda params, t, p, sm, bt, cl, cache: decode_step(
                params, t, p, sm, bt, cl, cache, c, block_size=bs,
                attn_impl="xla",
            ),
            donate_argnums=(6,),
        )

    # -- internals ------------------------------------------------------------

    def _bt(self, seq: SequenceBlocks) -> "np.ndarray":
        w = max(1, 1 << (max(1, len(seq.blocks)) - 1).bit_length())
        bt = np.zeros((1, w), np.int32)
        bt[0, : len(seq.blocks)] = seq.blocks
        return bt

    def _feed_chunk(self, seq: SequenceBlocks, chunk: list, start: int):
        """Prefill `chunk` at absolute positions start.. -> last logits."""
        import jax.numpy as jnp

        num_slots = self.kv.num_slots
        S_pad = max(8, 1 << (len(chunk) - 1).bit_length())
        tokens = np.zeros((1, S_pad), np.int32)
        tokens[0, : len(chunk)] = chunk
        positions = np.zeros((1, S_pad), np.int32)
        positions[0, : len(chunk)] = np.arange(start, start + len(chunk))
        slots = np.full((1, S_pad), num_slots, np.int32)
        for i, p in enumerate(range(start, start + len(chunk))):
            slots[0, i] = seq.slot(p)
        logits, self.cache = self._prefill(
            self.params,
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray([len(chunk)], jnp.int32),
            jnp.asarray(slots),
            jnp.asarray(self._bt(seq)),
            jnp.asarray([start + len(chunk)], jnp.int32),
            self.cache,
        )
        return logits

    # -- Drafter API ----------------------------------------------------------

    def propose(self, request_id: str, tokens: list, k: int) -> list:
        import jax.numpy as jnp

        c = self.config
        if len(tokens) + k >= c.max_seq:
            k = c.max_seq - 1 - len(tokens)
        if k <= 0:
            return []
        st = self._states.get(request_id)
        if st is None:
            st = {"seq": SequenceBlocks(self.allocator), "hist": []}
            self._states[request_id] = st
        seq, hist = st["seq"], st["hist"]

        # sync by longest common prefix: a rejected draft shows up here
        # as a mismatch and rolls the draft KV back with truncate_to
        common = 0
        for a, b in zip(hist, tokens):
            if a != b:
                break
            common += 1
        if common == len(tokens):
            # everything already fed (shouldn't happen: the engine always
            # appends >=1 new token per step) — re-feed the last token
            common = len(tokens) - 1
        if common < len(hist):
            seq.truncate_to(common)
            del hist[common:]

        try:
            seq.ensure_capacity(len(tokens) + k)
        except NoFreeBlocksError:
            # draft cache full: drop this request's draft state entirely —
            # drafting is best-effort, the target engine never blocks on it
            self.release(request_id)
            return []

        # feed the history delta (bounded chunks keep pad buckets small)
        logits = None
        pos = common
        missing = tokens[common:]
        while missing:
            chunk = missing[:128]
            logits = self._feed_chunk(seq, chunk, pos)
            hist.extend(chunk)
            pos += len(chunk)
            missing = missing[len(chunk):]
        seq.num_tokens = len(tokens)

        # greedy extension: k decode steps on the draft cache
        drafted: list = []
        tok = int(jnp.argmax(logits[0]))
        for _ in range(k):
            drafted.append(tok)
            p = len(tokens) + len(drafted) - 1
            logits, self.cache = self._decode(
                self.params,
                jnp.asarray([tok], jnp.int32),
                jnp.asarray([p], jnp.int32),
                jnp.asarray([seq.slot(p)], jnp.int32),
                jnp.asarray(self._bt(seq)),
                jnp.asarray([p + 1], jnp.int32),
                self.cache,
            )
            tok = int(jnp.argmax(logits[0]))
        hist.extend(drafted)
        seq.num_tokens = len(tokens) + len(drafted)
        return drafted

    def release(self, request_id: str) -> None:
        st = self._states.pop(request_id, None)
        if st is not None:
            st["seq"].release()
