"""Message-framed RPC over TCP for the distributed control plane.

Reference analog: src/ray/rpc/ (gRPC client/server wrappers, client
pools, retryable clients). Redesigned, not ported: the control plane
speaks length-prefixed pickled frames over asyncio TCP — no protoc
toolchain in the loop, and the payloads are plain Python structures the
rest of the runtime already uses. The wire format:

    frame    := uint32 length | pickled body
    request  := (msg_id, method: str, payload[, hterm])
    response := (msg_id, ok: bool, payload | exception[, term])

The optional 4th element is the HA fencing-term envelope (cluster/ha.py):
GCS-bound requests carry the highest fencing term the client has seen;
GCS responses carry the server's current term. A server whose handler
exposes ``ha_fence``/``ha_term`` rejects mutations carrying a newer term
than its own (it is a deposed zombie primary), and a client that sees a
response term below its own high-water mark discards the ack (it came
from a stale primary). Non-HA servers and old peers simply omit the
element — 3-tuples remain fully valid on both sides.

Servers run an asyncio loop on a dedicated thread and dispatch to a
handler object's `rpc_<method>` coroutines/functions. Clients are
thread-safe: one persistent connection, pipelined requests matched by
msg_id (the reference's CoreWorkerClientPool plays this role).

Security note: peers are trusted (same-user local processes / cluster
hosts), exactly like the reference's raylet protocol.
"""

from __future__ import annotations

import asyncio
import pickle
import socket
import struct
import threading
import time
from typing import Any, Callable, Optional

from ray_tpu.chaos import harness as _chaos
from ray_tpu.util.backoff import ExponentialBackoff
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.cluster.rpc")

_LEN = struct.Struct("!I")
MAX_FRAME = 1 << 31


class RpcError(Exception):
    """Transport-level failure (peer died, connection refused)."""


class NotPrimaryError(RpcError):
    """The peer is not the serving GCS primary: an unpromoted standby, or
    a deposed (fenced) primary whose term is stale. Callers holding an
    endpoint list fail over to the next endpoint instead of surfacing
    this (ReconnectingRpcClient does that internally)."""

    def __init__(self, message: str, term: int = 0):
        super().__init__(message)
        self.term = int(term)


class StaleTermError(RpcError):
    """A response arrived stamped with a fencing term below this client's
    high-water mark — the ack came from a zombie primary and must not be
    trusted (its state is doomed to be discarded at reconciliation)."""


class RemoteError(Exception):
    """The remote handler raised; carries the original exception."""

    def __init__(self, cause: BaseException):
        super().__init__(repr(cause))
        self.cause = cause


class TermTracker:
    """Highest GCS fencing term this client has observed. Shared across
    the clients of one control plane so a term learned from the promoted
    standby immediately fences requests sent to the old primary."""

    def __init__(self) -> None:
        self._term = 0
        self._lock = threading.Lock()

    @property
    def current(self) -> int:
        return self._term

    def observe(self, term) -> int:
        if term is None:
            return self._term
        with self._lock:
            if term > self._term:
                self._term = int(term)
            return self._term


def _normalize_endpoints(host, port=None, extra=()) -> list[tuple[str, int]]:
    """Accept every shape a GCS address travels in: ("h", p) pairs,
    a single (h, p) tuple, or an ordered endpoint list ((h1, p1),
    (h2, p2), ...) — the two-endpoint HA deployment splats through the
    same ``Client(*gcs_addr)`` call sites the single-address form uses."""
    if isinstance(host, str):
        if port is None:
            raise ValueError(f"endpoint {host!r} needs a port")
        eps = [(host, int(port))]
        eps.extend((h, int(p)) for h, p in extra)
        return eps
    first = tuple(host)
    if len(first) == 2 and isinstance(first[0], str):
        eps = [(first[0], int(first[1]))]
    else:
        eps = [(h, int(p)) for h, p in first]
    if port is not None:
        eps.append((port[0], int(port[1])))
    eps.extend((h, int(p)) for h, p in extra)
    return eps


def format_gcs_addr(addr) -> str:
    """'h1:p1[,h2:p2...]' — the --gcs flag form of a (possibly
    multi-endpoint) GCS address."""
    return ",".join(f"{h}:{p}" for h, p in _normalize_endpoints(addr))


def parse_gcs_addr(s: str):
    """Inverse of format_gcs_addr. A single endpoint parses to the legacy
    (host, port) tuple so existing addr[0]/addr[1] consumers keep
    working; multiple parse to an ordered endpoint tuple."""
    eps = []
    for part in s.split(","):
        h, p = part.rsplit(":", 1)
        eps.append((h, int(p)))
    return eps[0] if len(eps) == 1 else tuple(eps)


def _dump(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=5)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class RpcServer:
    """Asyncio TCP server on its own thread; dispatches `rpc_<method>`.

    Handlers may be plain functions or coroutines. A handler may also be
    registered per-method via `route`. The handler receives (payload,
    peer) where peer is a ("host", port) tuple of the connection.
    """

    def __init__(self, handler: Any = None, host: str = "127.0.0.1", port: int = 0):
        self._handler = handler
        self._routes: dict[str, Callable] = {}
        self._host = host
        self._port = port
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self.address: Optional[tuple[str, int]] = None
        # per-method server latency histogram (obs.perfwatch): built once
        # here, observed per dispatch — the sharding work needs to know
        # WHICH control-plane methods pay before partitioning anything
        from ray_tpu.cluster.lockstats import rpc_latency_histogram

        self._latency_hist = rpc_latency_histogram()

    def route(self, method: str, fn: Callable) -> None:
        self._routes[method] = fn

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> tuple[str, int]:
        self._thread = threading.Thread(
            target=self._run, name="ray_tpu-rpc-server", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RpcError("rpc server failed to start")
        assert self.address is not None
        return self.address

    def _run(self) -> None:
        from concurrent.futures import ThreadPoolExecutor

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        # sync handlers run in this pool; blocking calls (object fetch,
        # task execution) must never occupy the event loop thread
        self._loop.set_default_executor(
            ThreadPoolExecutor(max_workers=64, thread_name_prefix="rpc-handler")
        )
        self._loop.run_until_complete(self._serve())
        try:
            self._loop.run_forever()
        finally:
            self._loop.close()

    async def _serve(self) -> None:
        self._server = await asyncio.start_server(
            self._on_conn, self._host, self._port
        )
        self.address = self._server.sockets[0].getsockname()[:2]
        self._started.set()

    def stop(self) -> None:
        loop = self._loop
        if loop is None:
            return

        def _stop():
            if self._server is not None:
                self._server.close()
            loop.stop()

        try:
            loop.call_soon_threadsafe(_stop)
        except RuntimeError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)

    @property
    def loop(self) -> asyncio.AbstractEventLoop:
        assert self._loop is not None
        return self._loop

    def call_soon(self, fn: Callable, *args) -> None:
        """Schedule fn on the server loop (for timers/background work)."""
        self.loop.call_soon_threadsafe(fn, *args)

    # -- connection handling --------------------------------------------------

    async def _on_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        write_lock = asyncio.Lock()
        try:
            while True:
                hdr = await reader.readexactly(_LEN.size)
                (n,) = _LEN.unpack(hdr)
                if n > MAX_FRAME:
                    raise RpcError(f"frame too large: {n}")
                body = await reader.readexactly(n)
                try:
                    rec = pickle.loads(body)
                    msg_id, method, payload = rec[0], rec[1], rec[2]
                    hterm = rec[3] if len(rec) > 3 else None
                except Exception as e:  # noqa: BLE001 — torn/corrupted frame
                    # a corrupted frame (bit flip, truncated writer) poisons
                    # the whole stream (framing offsets are gone): drop the
                    # CONNECTION, not the server — the peer re-dials
                    logger.warning(
                        "dropping connection from %s: undecodable frame (%r)",
                        peer, e,
                    )
                    break
                # concurrent dispatch: a slow handler must not block the
                # connection (the reference runs handlers on thread pools)
                asyncio.ensure_future(
                    self._dispatch(
                        msg_id, method, payload, hterm, peer, writer, write_lock
                    )
                )
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(
        self, msg_id, method, payload, hterm, peer, writer, write_lock
    ) -> None:
        t0 = time.perf_counter()
        term_of = getattr(self._handler, "ha_term", None)

        def _respond(ok, result):
            # stamp the server's CURRENT term (post-handler: a promotion
            # mid-call must not be masked by a stale pre-read)
            t = None
            if term_of is not None:
                try:
                    t = term_of()
                except Exception:  # noqa: BLE001
                    t = None
            rec = (msg_id, ok, result) if t is None else (msg_id, ok, result, t)
            return _dump(rec)

        try:
            fence = getattr(self._handler, "ha_fence", None)
            if fence is not None and hterm is not None:
                # fencing-term check BEFORE the handler runs: a request
                # carrying a newer term proves this server was deposed —
                # it must reject the mutation, not execute it (the
                # split-brain guard; cluster/ha.py)
                verdict = fence(hterm, method)
                if verdict is not None:
                    raise verdict
            fn = self._routes.get(method) or getattr(self._handler, f"rpc_{method}")
            if asyncio.iscoroutinefunction(fn):
                result = await fn(payload, peer)
            else:
                # plain handlers may block (fetch, exec): keep the loop free
                result = await asyncio.get_running_loop().run_in_executor(
                    None, fn, payload, peer
                )
                if asyncio.iscoroutine(result):
                    result = await result
            body = _respond(True, result)
        except BaseException as e:  # noqa: BLE001 - serialized to caller
            try:
                body = _respond(False, e)
            except Exception:
                body = _respond(False, RpcError(repr(e)))
        # handler latency including executor queueing (that queue IS part
        # of what a caller experiences), excluding the response write
        self._latency_hist.observe(
            (time.perf_counter() - t0) * 1e3, {"method": str(method)}
        )
        async with write_lock:
            try:
                writer.write(_LEN.pack(len(body)) + body)
                await writer.drain()
            except (ConnectionError, RuntimeError):
                pass


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class RpcClient:
    """Thread-safe pipelined client over one persistent connection."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.addr = (host, port)
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._wlock = threading.Lock()
        self._next_id = 0
        self._pending: dict[int, tuple[threading.Event, list]] = {}
        self._plock = threading.Lock()
        self._reader: Optional[threading.Thread] = None
        self._closed = False
        self._dead = False  # reader saw the peer vanish
        # HA term observer: set by ReconnectingRpcClient so every stamped
        # response feeds the shared TermTracker high-water mark
        self.on_term: Optional[Callable[[int], Any]] = None

    # -- connection -----------------------------------------------------------

    def connect(self, retries: int = 0, delay: float = 0.1) -> "RpcClient":
        last: Optional[BaseException] = None
        # cap never below the caller's base delay: connect(delay=3.0) is
        # a legal request for slow dials, not a constructor error
        backoff = ExponentialBackoff(base=delay, cap=max(2.0, delay))
        for _ in range(retries + 1):
            try:
                s = socket.create_connection(self.addr, timeout=self._timeout)
                # back to BLOCKING mode: create_connection's timeout must
                # not linger on the connected socket — a timeout-mode
                # sendall can give up MID-FRAME (bytes written:
                # indeterminate) and corrupt the stream for every pending
                # call. Sends block (python path; the native writer has
                # its own bounded poll); the read loop bounds itself with
                # select() without touching socket-wide state.
                s.settimeout(None)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._sock = s
                self._native = None
                try:
                    from ray_tpu.native import framing as _framing

                    if _framing.enabled():
                        self._native = _framing.load_library()
                except Exception:  # noqa: BLE001 — toolchain missing
                    self._native = None
                self._reader = threading.Thread(
                    target=self._read_loop, name="ray_tpu-rpc-client", daemon=True
                )
                self._reader.start()
                return self
            except OSError as e:
                last = e
                backoff.sleep()
        raise RpcError(f"cannot connect to {self.addr}: {last}")

    def close(self) -> None:
        self._closed = True
        if self._sock is not None:
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        self._fail_all(RpcError(f"connection to {self.addr} closed"))

    @property
    def connected(self) -> bool:
        return self._sock is not None and not self._closed and not self._dead

    # -- calls ----------------------------------------------------------------

    def call(self, method: str, payload: Any = None,
             timeout: Optional[float] = None,
             hterm: Optional[int] = None) -> Any:
        if self._sock is None:
            raise RpcError("not connected")
        if self._dead:
            raise RpcError(f"connection to {self.addr} is dead")
        if _chaos.ACTIVE is not None:
            # fault injection BEFORE the pending-slot registration so a
            # dropped call leaves no orphaned waiter
            for _f in _chaos.fire(
                "rpc.call",
                kinds=(_chaos.DROP_RPC, _chaos.DELAY_RPC,
                       _chaos.PARTIAL_PARTITION),
                method=method, peer=f"{self.addr[0]}:{self.addr[1]}",
            ):
                if _f.kind == _chaos.DELAY_RPC:
                    time.sleep(_f.delay_s)
                elif _f.kind == _chaos.DROP_RPC:
                    raise RpcError(
                        f"chaos: dropped rpc {method!r} to {self.addr}"
                    )
                elif _f.kind == _chaos.PARTIAL_PARTITION:
                    # rpc/daemon-layer partition: the matched methods
                    # (typically the collective KV plane — match on
                    # method="kv_*") become unreachable while everything
                    # unmatched, e.g. the daemon's heartbeats, still
                    # flows. ClusterGroup maps this RpcError to the
                    # typed CollectivePartitionError.
                    raise RpcError(
                        f"chaos: partial partition — {method!r} to "
                        f"{self.addr} unreachable (unmatched control "
                        "traffic unaffected)"
                    )
        with self._plock:
            msg_id = self._next_id
            self._next_id += 1
            ev: tuple[threading.Event, list] = (threading.Event(), [])
            self._pending[msg_id] = ev
        body = _dump(
            (msg_id, method, payload) if hterm is None
            else (msg_id, method, payload, hterm)
        )
        if len(body) > MAX_FRAME:
            # mirror the server's read-side limit BEFORE the uint32 length
            # prefix overflows: disaggregated KV handoffs make multi-MB
            # frames routine, and an oversized one must fail loudly here,
            # not poison the stream for every pipelined caller
            with self._plock:
                self._pending.pop(msg_id, None)
            raise RpcError(
                f"rpc {method!r} frame of {len(body)} bytes exceeds "
                f"MAX_FRAME={MAX_FRAME}; chunk the payload"
            )
        if _chaos.ACTIVE is not None:
            for _f in _chaos.fire(
                "rpc.frame", kinds=(_chaos.CORRUPT_FRAME,),
                method=method, peer=f"{self.addr[0]}:{self.addr[1]}",
            ):
                if _f.kind == _chaos.CORRUPT_FRAME:
                    # the peer reads a full frame, fails to decode it, and
                    # drops the connection — the realistic torn-wire mode
                    body = _chaos.corrupt_frame(body)
        try:
            with self._wlock:
                native = getattr(self, "_native", None)
                if native is not None:
                    # one writev of header+payload in C, GIL released.
                    # Bounded poll derived from the client timeout: a
                    # stalled peer must not wedge _wlock (and with it
                    # every thread on this connection) forever
                    if native.frame_write(
                        self._sock.fileno(), body, len(body),
                        int(self._timeout * 1000),
                    ) != 0:
                        raise OSError("native frame_write failed or timed out")
                else:
                    self._sock.sendall(_LEN.pack(len(body)) + body)
        except OSError as e:
            with self._plock:
                self._pending.pop(msg_id, None)
            # a failed send never delivered a complete frame (length-
            # prefixed framing: partial writes are never executed), so
            # this connection is DEAD and the call is safe to retry on a
            # fresh dial — don't wait for the reader to notice the EOF
            self._dead = True
            raise RpcError(f"send to {self.addr} failed: {e}") from e
        if not ev[0].wait(timeout if timeout is not None else self._timeout):
            with self._plock:
                self._pending.pop(msg_id, None)
            raise RpcError(f"rpc {method} to {self.addr} timed out")
        ok, result, term = ev[1]
        if term is not None and self.on_term is not None:
            self.on_term(term)
        if isinstance(result, RpcError) and not ok:
            raise result
        if not ok:
            raise RemoteError(result)
        if hterm is not None and term is not None and term < hterm:
            # success ack from a server whose term is below our high-water
            # mark: a zombie primary's late ack. Its table write is doomed
            # (the promoted standby's reconcile discards it) — surfacing
            # the ack as success would invent state the cluster never sees
            raise StaleTermError(
                f"rpc {method}: ack from {self.addr} at stale term "
                f"{term} < {hterm}"
            )
        return result

    def _read_loop(self) -> None:
        sock = self._sock
        assert sock is not None
        native = None
        try:
            from ray_tpu.native import framing as _framing

            if _framing.enabled():
                # opt-in native receive loop: blocks in C with the GIL
                # released, one malloc per frame (src/framing.cc). Idle
                # polls are bounded so the loop re-checks _closed; a
                # mid-frame stall past the client timeout reads as
                # connection loss instead of wedging the reader thread
                native = _framing.FrameReader(
                    sock.fileno(),
                    timeout_ms=int(self._timeout * 1000),
                    should_stop=lambda: self._closed,
                )
        except Exception:  # noqa: BLE001 — build/toolchain missing: Python path
            native = None
        buf = b""

        def _recv_more(mid_frame: bool) -> bytes:
            """One bounded recv via select() readability polls (NOT
            settimeout — timeout mode applies socket-wide and would make
            the writer thread's sendall fail spuriously mid-frame on any
            >0.25s send). Idle polls re-check _closed; a peer that stalls
            MID-FRAME past the client timeout reads as connection loss
            instead of wedging this thread (and every caller's pending
            slot) forever."""
            import select

            stall_deadline = time.monotonic() + self._timeout
            while not self._closed:
                readable, _, _ = select.select([sock], [], [], 0.25)
                if not readable:
                    if mid_frame and time.monotonic() >= stall_deadline:
                        raise ConnectionError(
                            f"peer stalled mid-frame > {self._timeout}s"
                        )
                    continue
                chunk = sock.recv(1 << 20)
                if not chunk:
                    raise ConnectionError("peer closed")
                return chunk
            raise ConnectionError("client closed")

        try:
            while not self._closed:
                if native is not None:
                    body = native.read_frame()
                    if body is None:
                        raise ConnectionError("peer closed")
                else:
                    while len(buf) < _LEN.size:
                        buf += _recv_more(mid_frame=bool(buf))
                    (n,) = _LEN.unpack(buf[: _LEN.size])
                    while len(buf) < _LEN.size + n:
                        buf += _recv_more(mid_frame=True)
                    body = buf[_LEN.size : _LEN.size + n]
                    buf = buf[_LEN.size + n :]
                rec = pickle.loads(body)
                msg_id, ok, result = rec[0], rec[1], rec[2]
                with self._plock:
                    ev = self._pending.pop(msg_id, None)
                if ev is not None:
                    ev[1][:] = [ok, result, rec[3] if len(rec) > 3 else None]
                    ev[0].set()
        except (ConnectionError, OSError, MemoryError) as e:
            self._fail_all(RpcError(f"connection to {self.addr} lost: {e}"))

    def _fail_all(self, err: RpcError) -> None:
        self._dead = True  # pool must re-dial, callers must fail fast
        with self._plock:
            pending = list(self._pending.values())
            self._pending.clear()
        for ev, slot in pending:
            slot[:] = [False, err, None]
            ev.set()


class ReconnectingRpcClient:
    """RpcClient that re-dials on a dead connection — the peer (e.g. a
    restarted GCS) may come back at the same address (reference: raylets
    reconnect to a Redis-restored GCS, gcs_redis_failure_detector.cc).

    HA extension (cluster/ha.py): accepts an ORDERED endpoint list —
    ``ReconnectingRpcClient(("h1", p1), ("h2", p2))`` or the splatted
    ``*gcs_addr`` form where gcs_addr is a tuple of endpoints — and
    fails over on connect errors, dead peers, and NotPrimaryError /
    StaleTermError rejections. Every call carries the highest fencing
    term seen (shared TermTracker) and every stamped response feeds it,
    so one client learning of a promotion fences the whole process's
    view of the old primary.
    """

    def __init__(self, host, port=None, *extra, timeout: float = 30.0,
                 retries: int = 20, redial_attempts: int = 3,
                 failover_attempts: int = 10,
                 term_tracker: Optional[TermTracker] = None):
        self._endpoints = _normalize_endpoints(host, port, extra)
        self._active = 0
        self.addr = self._endpoints[0]
        self._timeout = timeout
        self._retries = retries
        # dead-peer calls get up to this many fresh-dial retries (each
        # dial itself retries `retries` times) with jittered backoff —
        # a GCS that takes a few seconds to restart no longer fails the
        # caller on the single old immediate retry
        self._redial_attempts = max(1, int(redial_attempts))
        # not-primary hops are bounded separately: with backoff these
        # cover a full lease-expiry promotion window (~seconds) before
        # the rejection surfaces to the caller
        self._failover_attempts = max(1, int(failover_attempts))
        self.term = term_tracker if term_tracker is not None else TermTracker()
        self._lock = threading.Lock()
        self._client: Optional[RpcClient] = None
        self._closed = False

    @property
    def endpoints(self) -> tuple[tuple[str, int], ...]:
        return tuple(self._endpoints)

    def _dial_one(self, ep: tuple[str, int], retries: int) -> RpcClient:
        c = RpcClient(ep[0], ep[1], timeout=self._timeout).connect(
            retries=retries
        )
        c.on_term = self.term.observe
        return c

    def _commit(self, c: RpcClient, idx: int) -> RpcClient:
        with self._lock:
            if self._closed:
                c.close()
                raise RpcError(f"client to {self.addr} closed")
            existing = self._client
            if existing is not None and existing.connected:
                # another thread won the dial race; keep theirs
                c.close()
                return existing
            self._client = c
            self._active = idx
            self.addr = self._endpoints[idx]
            return c

    def _get(self) -> RpcClient:
        with self._lock:
            if self._closed:
                raise RpcError(f"client to {self.addr} closed")
            c = self._client
            if c is not None and c.connected:
                return c
            start = self._active
        # dial OUTSIDE the lock (same discipline as ClientPool.get):
        # holding _lock through a connect timeout x retries would wedge
        # every concurrent caller behind one dead peer
        if len(self._endpoints) == 1:
            c = self._dial_one(self._endpoints[0], self._retries)
            return self._commit(c, 0)
        # multi-endpoint: sweep the ordered list round-robin from the
        # last-good endpoint. Each endpoint gets ONE dial per round (a
        # dead primary costs one refused connect, not retries x backoff);
        # rounds are bounded by the configured retry budget.
        last: Optional[BaseException] = None
        backoff = ExponentialBackoff(base=0.05, cap=1.0)
        for _round in range(self._retries + 1):
            for k in range(len(self._endpoints)):
                idx = (start + k) % len(self._endpoints)
                ep = self._endpoints[idx]
                if _chaos.BLOCKED_PEERS and tuple(ep) in _chaos.BLOCKED_PEERS:
                    # chaos partition (PARTITION_GCS_PAIR): this peer is
                    # unreachable from here; try the others
                    last = RpcError(f"chaos: peer {ep} partitioned")
                    continue
                try:
                    c = self._dial_one(ep, 0)
                except RpcError as e:
                    last = e
                    continue
                return self._commit(c, idx)
            backoff.sleep()
        raise RpcError(f"cannot connect to any of {self._endpoints}: {last}")

    def _rotate(self, dead: RpcClient) -> None:
        """Drop a dead/rejected connection and advance to the next
        endpoint so the following _get() dials somewhere else first."""
        with self._lock:
            if self._client is dead:
                self._client = None
                if len(self._endpoints) > 1:
                    self._active = (self._active + 1) % len(self._endpoints)
                    self.addr = self._endpoints[self._active]
        try:
            dead.close()
        except Exception:  # noqa: BLE001
            pass

    def connect(self, retries: Optional[int] = None,
                delay: float = 0.1) -> "ReconnectingRpcClient":
        if retries is not None:
            self._retries = retries
        self._get()
        return self

    def call(self, method: str, payload: Any = None,
             timeout: Optional[float] = None) -> Any:
        if _chaos.ACTIVE is not None:
            # STALL_GCS: a control-plane outage WITHOUT a process death —
            # this client class is only ever pointed at the GCS, so the
            # hook covers exactly the GCS-bound plane. The seeded window
            # (start_after/every_n/max_fires over this process's call
            # order) fails each covered call with transport loss; callers
            # must degrade exactly as they would for a dead GCS.
            for _f in _chaos.fire(
                "gcs.call", kinds=(_chaos.STALL_GCS,),
                method=method, peer=f"{self.addr[0]}:{self.addr[1]}",
            ):
                if _f.kind == _chaos.STALL_GCS:
                    raise RpcError(
                        f"chaos: GCS stalled — {method!r} to {self.addr} "
                        "lost in the outage window"
                    )
        multi = len(self._endpoints) > 1
        backoff = None
        redials = 0
        hops = 0
        while True:
            c = self._get()
            if _chaos.BLOCKED_PEERS and tuple(c.addr) in _chaos.BLOCKED_PEERS:
                # the endpoint got partitioned AFTER we connected: the
                # cached connection is unusable, rotate off it
                self._rotate(c)
                if redials >= self._redial_attempts:
                    raise RpcError(f"chaos: peer {c.addr} partitioned")
                redials += 1
                if backoff is None:
                    backoff = ExponentialBackoff(base=0.05, cap=1.0)
                backoff.sleep()
                continue
            try:
                return c.call(method, payload, timeout,
                              hterm=self.term.current)
            except (NotPrimaryError, StaleTermError):
                # wrong peer for this plane: an unpromoted standby, or a
                # deposed zombie whose ack we must discard. With an
                # endpoint list, hop to the next endpoint — bounded hops
                # with backoff ride out the promotion window.
                if not multi or hops >= self._failover_attempts:
                    raise
                hops += 1
                self._rotate(c)
                if backoff is None:
                    backoff = ExponentialBackoff(base=0.05, cap=1.0)
                backoff.sleep()
            except RpcError:
                if c.connected:
                    # plain timeout on a live connection: the request may
                    # still execute — resending would make mutations
                    # at-least-once, so surface the error. But the
                    # connection itself is now suspect (a wedged or
                    # half-dead primary times out forever without EOF):
                    # with an endpoint list, drop it so the CALLER's
                    # retry dials the next endpoint instead of timing
                    # out against the same socket indefinitely.
                    if multi:
                        self._rotate(c)
                    raise
                # dead peer (e.g. restarted GCS): bounded fresh-dial
                # retries with jittered backoff (capped), not one shot.
                # With an endpoint list the retry dials the NEXT endpoint
                # first — this is the connect/timeout failover path.
                if redials >= self._redial_attempts:
                    raise
                redials += 1
                if multi:
                    self._rotate(c)
                if backoff is None:
                    backoff = ExponentialBackoff(base=0.05, cap=1.0)
                backoff.sleep()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._client is not None:
                self._client.close()
                self._client = None


class ClientPool:
    """Cache of RpcClients keyed by address (reference: client pools in
    src/ray/rpc/). Dead clients are evicted and re-dialed on next use."""

    def __init__(self, timeout: float = 30.0):
        self._clients: dict[tuple[str, int], RpcClient] = {}
        self._lock = threading.Lock()
        self._timeout = timeout

    def get(self, addr: tuple[str, int]) -> RpcClient:
        addr = (addr[0], int(addr[1]))
        with self._lock:
            c = self._clients.get(addr)
            if c is not None and c.connected:
                return c
        # dial OUTSIDE the lock: holding it through a connect timeout
        # would serialize every other address behind one wedged peer
        c = RpcClient(addr[0], addr[1], timeout=self._timeout).connect(retries=2)
        with self._lock:
            existing = self._clients.get(addr)
            if existing is not None and existing.connected:
                # another thread won the dial race; keep theirs
                c.close()
                return existing
            self._clients[addr] = c
            return c

    def invalidate(self, addr: tuple[str, int]) -> None:
        with self._lock:
            c = self._clients.pop((addr[0], int(addr[1])), None)
        if c is not None:
            c.close()

    def close_all(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            c.close()
