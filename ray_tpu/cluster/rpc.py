"""Message-framed RPC over TCP for the distributed control plane.

Reference analog: src/ray/rpc/ (gRPC client/server wrappers, client
pools, retryable clients). Redesigned, not ported: the control plane
speaks length-prefixed pickled frames over asyncio TCP — no protoc
toolchain in the loop, and the payloads are plain Python structures the
rest of the runtime already uses. The wire format:

    frame    := uint32 length | pickled body
    request  := (msg_id, method: str, payload)
    response := (msg_id, ok: bool, payload | exception)

Servers run an asyncio loop on a dedicated thread and dispatch to a
handler object's `rpc_<method>` coroutines/functions. Clients are
thread-safe: one persistent connection, pipelined requests matched by
msg_id (the reference's CoreWorkerClientPool plays this role).

Security note: peers are trusted (same-user local processes / cluster
hosts), exactly like the reference's raylet protocol.
"""

from __future__ import annotations

import asyncio
import pickle
import socket
import struct
import threading
import time
from typing import Any, Callable, Optional

from ray_tpu.chaos import harness as _chaos
from ray_tpu.util.backoff import ExponentialBackoff
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.cluster.rpc")

_LEN = struct.Struct("!I")
MAX_FRAME = 1 << 31


class RpcError(Exception):
    """Transport-level failure (peer died, connection refused)."""


class RemoteError(Exception):
    """The remote handler raised; carries the original exception."""

    def __init__(self, cause: BaseException):
        super().__init__(repr(cause))
        self.cause = cause


def _dump(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=5)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class RpcServer:
    """Asyncio TCP server on its own thread; dispatches `rpc_<method>`.

    Handlers may be plain functions or coroutines. A handler may also be
    registered per-method via `route`. The handler receives (payload,
    peer) where peer is a ("host", port) tuple of the connection.
    """

    def __init__(self, handler: Any = None, host: str = "127.0.0.1", port: int = 0):
        self._handler = handler
        self._routes: dict[str, Callable] = {}
        self._host = host
        self._port = port
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self.address: Optional[tuple[str, int]] = None
        # per-method server latency histogram (obs.perfwatch): built once
        # here, observed per dispatch — the sharding work needs to know
        # WHICH control-plane methods pay before partitioning anything
        from ray_tpu.cluster.lockstats import rpc_latency_histogram

        self._latency_hist = rpc_latency_histogram()

    def route(self, method: str, fn: Callable) -> None:
        self._routes[method] = fn

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> tuple[str, int]:
        self._thread = threading.Thread(
            target=self._run, name="ray_tpu-rpc-server", daemon=True
        )
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RpcError("rpc server failed to start")
        assert self.address is not None
        return self.address

    def _run(self) -> None:
        from concurrent.futures import ThreadPoolExecutor

        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        # sync handlers run in this pool; blocking calls (object fetch,
        # task execution) must never occupy the event loop thread
        self._loop.set_default_executor(
            ThreadPoolExecutor(max_workers=64, thread_name_prefix="rpc-handler")
        )
        self._loop.run_until_complete(self._serve())
        try:
            self._loop.run_forever()
        finally:
            self._loop.close()

    async def _serve(self) -> None:
        self._server = await asyncio.start_server(
            self._on_conn, self._host, self._port
        )
        self.address = self._server.sockets[0].getsockname()[:2]
        self._started.set()

    def stop(self) -> None:
        loop = self._loop
        if loop is None:
            return

        def _stop():
            if self._server is not None:
                self._server.close()
            loop.stop()

        try:
            loop.call_soon_threadsafe(_stop)
        except RuntimeError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)

    @property
    def loop(self) -> asyncio.AbstractEventLoop:
        assert self._loop is not None
        return self._loop

    def call_soon(self, fn: Callable, *args) -> None:
        """Schedule fn on the server loop (for timers/background work)."""
        self.loop.call_soon_threadsafe(fn, *args)

    # -- connection handling --------------------------------------------------

    async def _on_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        write_lock = asyncio.Lock()
        try:
            while True:
                hdr = await reader.readexactly(_LEN.size)
                (n,) = _LEN.unpack(hdr)
                if n > MAX_FRAME:
                    raise RpcError(f"frame too large: {n}")
                body = await reader.readexactly(n)
                try:
                    msg_id, method, payload = pickle.loads(body)
                except Exception as e:  # noqa: BLE001 — torn/corrupted frame
                    # a corrupted frame (bit flip, truncated writer) poisons
                    # the whole stream (framing offsets are gone): drop the
                    # CONNECTION, not the server — the peer re-dials
                    logger.warning(
                        "dropping connection from %s: undecodable frame (%r)",
                        peer, e,
                    )
                    break
                # concurrent dispatch: a slow handler must not block the
                # connection (the reference runs handlers on thread pools)
                asyncio.ensure_future(
                    self._dispatch(msg_id, method, payload, peer, writer, write_lock)
                )
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(
        self, msg_id, method, payload, peer, writer, write_lock
    ) -> None:
        t0 = time.perf_counter()
        try:
            fn = self._routes.get(method) or getattr(self._handler, f"rpc_{method}")
            if asyncio.iscoroutinefunction(fn):
                result = await fn(payload, peer)
            else:
                # plain handlers may block (fetch, exec): keep the loop free
                result = await asyncio.get_running_loop().run_in_executor(
                    None, fn, payload, peer
                )
                if asyncio.iscoroutine(result):
                    result = await result
            body = _dump((msg_id, True, result))
        except BaseException as e:  # noqa: BLE001 - serialized to caller
            try:
                body = _dump((msg_id, False, e))
            except Exception:
                body = _dump((msg_id, False, RpcError(repr(e))))
        # handler latency including executor queueing (that queue IS part
        # of what a caller experiences), excluding the response write
        self._latency_hist.observe(
            (time.perf_counter() - t0) * 1e3, {"method": str(method)}
        )
        async with write_lock:
            try:
                writer.write(_LEN.pack(len(body)) + body)
                await writer.drain()
            except (ConnectionError, RuntimeError):
                pass


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class RpcClient:
    """Thread-safe pipelined client over one persistent connection."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.addr = (host, port)
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._wlock = threading.Lock()
        self._next_id = 0
        self._pending: dict[int, tuple[threading.Event, list]] = {}
        self._plock = threading.Lock()
        self._reader: Optional[threading.Thread] = None
        self._closed = False
        self._dead = False  # reader saw the peer vanish

    # -- connection -----------------------------------------------------------

    def connect(self, retries: int = 0, delay: float = 0.1) -> "RpcClient":
        last: Optional[BaseException] = None
        # cap never below the caller's base delay: connect(delay=3.0) is
        # a legal request for slow dials, not a constructor error
        backoff = ExponentialBackoff(base=delay, cap=max(2.0, delay))
        for _ in range(retries + 1):
            try:
                s = socket.create_connection(self.addr, timeout=self._timeout)
                # back to BLOCKING mode: create_connection's timeout must
                # not linger on the connected socket — a timeout-mode
                # sendall can give up MID-FRAME (bytes written:
                # indeterminate) and corrupt the stream for every pending
                # call. Sends block (python path; the native writer has
                # its own bounded poll); the read loop bounds itself with
                # select() without touching socket-wide state.
                s.settimeout(None)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._sock = s
                self._native = None
                try:
                    from ray_tpu.native import framing as _framing

                    if _framing.enabled():
                        self._native = _framing.load_library()
                except Exception:  # noqa: BLE001 — toolchain missing
                    self._native = None
                self._reader = threading.Thread(
                    target=self._read_loop, name="ray_tpu-rpc-client", daemon=True
                )
                self._reader.start()
                return self
            except OSError as e:
                last = e
                backoff.sleep()
        raise RpcError(f"cannot connect to {self.addr}: {last}")

    def close(self) -> None:
        self._closed = True
        if self._sock is not None:
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        self._fail_all(RpcError(f"connection to {self.addr} closed"))

    @property
    def connected(self) -> bool:
        return self._sock is not None and not self._closed and not self._dead

    # -- calls ----------------------------------------------------------------

    def call(self, method: str, payload: Any = None, timeout: Optional[float] = None) -> Any:
        if self._sock is None:
            raise RpcError("not connected")
        if self._dead:
            raise RpcError(f"connection to {self.addr} is dead")
        if _chaos.ACTIVE is not None:
            # fault injection BEFORE the pending-slot registration so a
            # dropped call leaves no orphaned waiter
            for _f in _chaos.fire(
                "rpc.call",
                kinds=(_chaos.DROP_RPC, _chaos.DELAY_RPC,
                       _chaos.PARTIAL_PARTITION),
                method=method, peer=f"{self.addr[0]}:{self.addr[1]}",
            ):
                if _f.kind == _chaos.DELAY_RPC:
                    time.sleep(_f.delay_s)
                elif _f.kind == _chaos.DROP_RPC:
                    raise RpcError(
                        f"chaos: dropped rpc {method!r} to {self.addr}"
                    )
                elif _f.kind == _chaos.PARTIAL_PARTITION:
                    # rpc/daemon-layer partition: the matched methods
                    # (typically the collective KV plane — match on
                    # method="kv_*") become unreachable while everything
                    # unmatched, e.g. the daemon's heartbeats, still
                    # flows. ClusterGroup maps this RpcError to the
                    # typed CollectivePartitionError.
                    raise RpcError(
                        f"chaos: partial partition — {method!r} to "
                        f"{self.addr} unreachable (unmatched control "
                        "traffic unaffected)"
                    )
        with self._plock:
            msg_id = self._next_id
            self._next_id += 1
            ev: tuple[threading.Event, list] = (threading.Event(), [])
            self._pending[msg_id] = ev
        body = _dump((msg_id, method, payload))
        if len(body) > MAX_FRAME:
            # mirror the server's read-side limit BEFORE the uint32 length
            # prefix overflows: disaggregated KV handoffs make multi-MB
            # frames routine, and an oversized one must fail loudly here,
            # not poison the stream for every pipelined caller
            with self._plock:
                self._pending.pop(msg_id, None)
            raise RpcError(
                f"rpc {method!r} frame of {len(body)} bytes exceeds "
                f"MAX_FRAME={MAX_FRAME}; chunk the payload"
            )
        if _chaos.ACTIVE is not None:
            for _f in _chaos.fire(
                "rpc.frame", kinds=(_chaos.CORRUPT_FRAME,),
                method=method, peer=f"{self.addr[0]}:{self.addr[1]}",
            ):
                if _f.kind == _chaos.CORRUPT_FRAME:
                    # the peer reads a full frame, fails to decode it, and
                    # drops the connection — the realistic torn-wire mode
                    body = _chaos.corrupt_frame(body)
        try:
            with self._wlock:
                native = getattr(self, "_native", None)
                if native is not None:
                    # one writev of header+payload in C, GIL released.
                    # Bounded poll derived from the client timeout: a
                    # stalled peer must not wedge _wlock (and with it
                    # every thread on this connection) forever
                    if native.frame_write(
                        self._sock.fileno(), body, len(body),
                        int(self._timeout * 1000),
                    ) != 0:
                        raise OSError("native frame_write failed or timed out")
                else:
                    self._sock.sendall(_LEN.pack(len(body)) + body)
        except OSError as e:
            with self._plock:
                self._pending.pop(msg_id, None)
            raise RpcError(f"send to {self.addr} failed: {e}") from e
        if not ev[0].wait(timeout if timeout is not None else self._timeout):
            with self._plock:
                self._pending.pop(msg_id, None)
            raise RpcError(f"rpc {method} to {self.addr} timed out")
        ok, result = ev[1]
        if isinstance(result, RpcError) and not ok:
            raise result
        if not ok:
            raise RemoteError(result)
        return result

    def _read_loop(self) -> None:
        sock = self._sock
        assert sock is not None
        native = None
        try:
            from ray_tpu.native import framing as _framing

            if _framing.enabled():
                # opt-in native receive loop: blocks in C with the GIL
                # released, one malloc per frame (src/framing.cc). Idle
                # polls are bounded so the loop re-checks _closed; a
                # mid-frame stall past the client timeout reads as
                # connection loss instead of wedging the reader thread
                native = _framing.FrameReader(
                    sock.fileno(),
                    timeout_ms=int(self._timeout * 1000),
                    should_stop=lambda: self._closed,
                )
        except Exception:  # noqa: BLE001 — build/toolchain missing: Python path
            native = None
        buf = b""

        def _recv_more(mid_frame: bool) -> bytes:
            """One bounded recv via select() readability polls (NOT
            settimeout — timeout mode applies socket-wide and would make
            the writer thread's sendall fail spuriously mid-frame on any
            >0.25s send). Idle polls re-check _closed; a peer that stalls
            MID-FRAME past the client timeout reads as connection loss
            instead of wedging this thread (and every caller's pending
            slot) forever."""
            import select

            stall_deadline = time.monotonic() + self._timeout
            while not self._closed:
                readable, _, _ = select.select([sock], [], [], 0.25)
                if not readable:
                    if mid_frame and time.monotonic() >= stall_deadline:
                        raise ConnectionError(
                            f"peer stalled mid-frame > {self._timeout}s"
                        )
                    continue
                chunk = sock.recv(1 << 20)
                if not chunk:
                    raise ConnectionError("peer closed")
                return chunk
            raise ConnectionError("client closed")

        try:
            while not self._closed:
                if native is not None:
                    body = native.read_frame()
                    if body is None:
                        raise ConnectionError("peer closed")
                else:
                    while len(buf) < _LEN.size:
                        buf += _recv_more(mid_frame=bool(buf))
                    (n,) = _LEN.unpack(buf[: _LEN.size])
                    while len(buf) < _LEN.size + n:
                        buf += _recv_more(mid_frame=True)
                    body = buf[_LEN.size : _LEN.size + n]
                    buf = buf[_LEN.size + n :]
                msg_id, ok, result = pickle.loads(body)
                with self._plock:
                    ev = self._pending.pop(msg_id, None)
                if ev is not None:
                    ev[1][:] = [ok, result]
                    ev[0].set()
        except (ConnectionError, OSError, MemoryError) as e:
            self._fail_all(RpcError(f"connection to {self.addr} lost: {e}"))

    def _fail_all(self, err: RpcError) -> None:
        self._dead = True  # pool must re-dial, callers must fail fast
        with self._plock:
            pending = list(self._pending.values())
            self._pending.clear()
        for ev, slot in pending:
            slot[:] = [False, err]
            ev.set()


class ReconnectingRpcClient:
    """RpcClient that re-dials on a dead connection — the peer (e.g. a
    restarted GCS) may come back at the same address (reference: raylets
    reconnect to a Redis-restored GCS, gcs_redis_failure_detector.cc)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 retries: int = 20, redial_attempts: int = 3):
        self.addr = (host, port)
        self._timeout = timeout
        self._retries = retries
        # dead-peer calls get up to this many fresh-dial retries (each
        # dial itself retries `retries` times) with jittered backoff —
        # a GCS that takes a few seconds to restart no longer fails the
        # caller on the single old immediate retry
        self._redial_attempts = max(1, int(redial_attempts))
        self._lock = threading.Lock()
        self._client: Optional[RpcClient] = None
        self._closed = False

    def _get(self) -> RpcClient:
        with self._lock:
            if self._closed:
                raise RpcError(f"client to {self.addr} closed")
            c = self._client
            if c is not None and c.connected:
                return c
        # dial OUTSIDE the lock (same discipline as ClientPool.get):
        # holding _lock through a connect timeout x retries would wedge
        # every concurrent caller behind one dead peer
        c = RpcClient(*self.addr, timeout=self._timeout).connect(
            retries=self._retries
        )
        with self._lock:
            if self._closed:
                c.close()
                raise RpcError(f"client to {self.addr} closed")
            existing = self._client
            if existing is not None and existing.connected:
                # another thread won the dial race; keep theirs
                c.close()
                return existing
            self._client = c
            return c

    def connect(self, retries: Optional[int] = None,
                delay: float = 0.1) -> "ReconnectingRpcClient":
        if retries is not None:
            self._retries = retries
        self._get()
        return self

    def call(self, method: str, payload: Any = None,
             timeout: Optional[float] = None) -> Any:
        if _chaos.ACTIVE is not None:
            # STALL_GCS: a control-plane outage WITHOUT a process death —
            # this client class is only ever pointed at the GCS, so the
            # hook covers exactly the GCS-bound plane. The seeded window
            # (start_after/every_n/max_fires over this process's call
            # order) fails each covered call with transport loss; callers
            # must degrade exactly as they would for a dead GCS.
            for _f in _chaos.fire(
                "gcs.call", kinds=(_chaos.STALL_GCS,),
                method=method, peer=f"{self.addr[0]}:{self.addr[1]}",
            ):
                if _f.kind == _chaos.STALL_GCS:
                    raise RpcError(
                        f"chaos: GCS stalled — {method!r} to {self.addr} "
                        "lost in the outage window"
                    )
        backoff = None
        for attempt in range(self._redial_attempts + 1):
            c = self._get()
            try:
                return c.call(method, payload, timeout)
            except RpcError:
                if c.connected:
                    # plain timeout on a live connection: the request may
                    # still execute — resending would make mutations
                    # at-least-once
                    raise
                # dead peer (e.g. restarted GCS): bounded fresh-dial
                # retries with jittered backoff (capped), not one shot
                if attempt >= self._redial_attempts:
                    raise
                if backoff is None:
                    backoff = ExponentialBackoff(base=0.05, cap=1.0)
                backoff.sleep()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._client is not None:
                self._client.close()
                self._client = None


class ClientPool:
    """Cache of RpcClients keyed by address (reference: client pools in
    src/ray/rpc/). Dead clients are evicted and re-dialed on next use."""

    def __init__(self, timeout: float = 30.0):
        self._clients: dict[tuple[str, int], RpcClient] = {}
        self._lock = threading.Lock()
        self._timeout = timeout

    def get(self, addr: tuple[str, int]) -> RpcClient:
        addr = (addr[0], int(addr[1]))
        with self._lock:
            c = self._clients.get(addr)
            if c is not None and c.connected:
                return c
        # dial OUTSIDE the lock: holding it through a connect timeout
        # would serialize every other address behind one wedged peer
        c = RpcClient(addr[0], addr[1], timeout=self._timeout).connect(retries=2)
        with self._lock:
            existing = self._clients.get(addr)
            if existing is not None and existing.connected:
                # another thread won the dial race; keep theirs
                c.close()
                return existing
            self._clients[addr] = c
            return c

    def invalidate(self, addr: tuple[str, int]) -> None:
        with self._lock:
            c = self._clients.pop((addr[0], int(addr[1])), None)
        if c is not None:
            c.close()

    def close_all(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            c.close()
