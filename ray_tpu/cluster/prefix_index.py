"""GCS-resident KV prefix index: chain hash -> {engine, tier, n_tokens}.

The control-plane half of ``ray_tpu.llm.kvtier`` — it lives under
``cluster/`` (not ``llm/``) so the GCS process can host the table
without importing the serving stack (jax stays out of the control
plane). Engine-side publishers and routing consumers import it back
through ``ray_tpu.llm.kvtier.index``.

Staleness discipline (the telemetry plane's): engines ship FULL
snapshots stamped (epoch, seq). A replayed or out-of-order snapshot is
dropped, never merged; a new epoch (engine restart) atomically replaces
the dead incarnation's rows; a weight swap ships an empty snapshot that
drops every stale row at once. The table is deliberately NOT persisted:
like telemetry it is a freshness surface — a restarted GCS repopulates
within one flush interval, and routers fall back to their queue-depth
ladder until it does.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

TIER_HBM = "hbm"
TIER_HOST = "host"
TIER_OBJECT = "object"

# wire codes (ints travel in snapshots; names render in lookups)
TIER_CODES = {TIER_HBM: 0, TIER_HOST: 1, TIER_OBJECT: 2}
TIER_NAMES = {v: k for k, v in TIER_CODES.items()}


class _EngineRows:
    __slots__ = ("epoch", "seq", "rows", "ts", "fetch_addr")

    def __init__(self, epoch: int, seq: int, rows: dict, ts: float,
                 fetch_addr=None):
        self.epoch = epoch
        self.seq = seq
        self.rows = rows  # chain_hash -> (tier_code, n_tokens)
        self.ts = ts
        # where remote engines can PULL this engine's spilled blocks
        # (llm/kvfetch RPC backend: a (host, port) pair; None for
        # in-process planes). Rides each snapshot so a restarted
        # engine's new address replaces the old one atomically.
        self.fetch_addr = fetch_addr


class PrefixIndexStore:
    """The index table. Thread-safe; snapshot-replace per engine."""

    def __init__(self, stale_after_s: float = 30.0,
                 expire_after_s: float = 180.0):
        self._lock = threading.Lock()
        self._engines: dict[str, _EngineRows] = {}
        self.stale_after_s = stale_after_s
        # reap horizon: uuid-keyed replicas churn, and a dead replica's
        # snapshot must not pin its rows (or inflate stats) forever —
        # entries silent past this are deleted outright (lookup already
        # stopped answering from them at stale_after_s)
        self.expire_after_s = expire_after_s
        self.num_updates = 0
        self.num_stale_dropped = 0
        self.num_expired = 0

    def _reap_locked(self, now: float) -> None:
        dead = [e for e, er in self._engines.items()
                if now - er.ts > self.expire_after_s]
        for e in dead:
            del self._engines[e]
            self.num_expired += 1

    def update(self, payload: dict) -> dict:
        """Apply one engine snapshot: {"engine", "epoch", "seq",
        "rows": [[hash, tier_code, n_tokens], ...]}. Stale (old epoch /
        replayed seq) snapshots are dropped, never merged."""
        engine = str(payload["engine"])
        epoch = int(payload.get("epoch", 0))
        seq = int(payload.get("seq", 0))
        rows = {int(h): (int(t), int(n)) for h, t, n in payload.get("rows", [])}
        with self._lock:
            self._reap_locked(time.time())
            cur = self._engines.get(engine)
            if cur is not None:
                if epoch < cur.epoch or (epoch == cur.epoch and seq <= cur.seq):
                    self.num_stale_dropped += 1
                    return {"ok": False, "reason": "stale"}
            self._engines[engine] = _EngineRows(
                epoch, seq, rows, time.time(),
                fetch_addr=payload.get("fetch_addr"),
            )
            self.num_updates += 1
        return {"ok": True}

    def drop_engine(self, engine: str) -> None:
        with self._lock:
            self._engines.pop(str(engine), None)

    def lookup(self, hashes: list) -> dict:
        """Longest indexed prefix per engine over the prompt's chain
        hashes. Returns {"engines": {engine: {"tier", "n_tokens",
        "age_s"}}} — engines whose snapshot has gone stale are omitted
        (routing treats them as holding nothing)."""
        now = time.time()
        want = [int(h) for h in hashes]
        out: dict[str, dict] = {}
        with self._lock:
            for engine, er in self._engines.items():
                age = now - er.ts
                if age > self.stale_after_s:
                    continue
                best: Optional[tuple] = None
                for h in want:
                    got = er.rows.get(h)
                    if got is None:
                        continue
                    tier_code, n = got
                    if best is None or n > best[1]:
                        best = (tier_code, n)
                if best is not None:
                    row = {
                        "tier": TIER_NAMES.get(best[0], TIER_OBJECT),
                        "n_tokens": best[1],
                        "age_s": round(age, 3),
                    }
                    if er.fetch_addr is not None:
                        # the kvfetch pull address: a replica that does
                        # NOT hold this prefix can fetch it from here
                        row["fetch_addr"] = er.fetch_addr
                    out[engine] = row
        return {"engines": out}

    def stats(self) -> dict:
        with self._lock:
            self._reap_locked(time.time())
            by_tier: dict[str, int] = {}
            for er in self._engines.values():
                for tier_code, _n in er.rows.values():
                    name = TIER_NAMES.get(tier_code, TIER_OBJECT)
                    by_tier[name] = by_tier.get(name, 0) + 1
            return {
                "engines": len(self._engines),
                "rows": sum(len(er.rows) for er in self._engines.values()),
                "rows_by_tier": by_tier,
                "updates": self.num_updates,
                "stale_dropped": self.num_stale_dropped,
                "expired": self.num_expired,
            }
