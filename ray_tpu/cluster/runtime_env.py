"""Runtime environments: per-task/actor worker environments.

Reference analog: python/ray/_private/runtime_env/ — the env_vars,
working_dir, and py_modules plugins with URI-addressed packaging (zips
staged through the GCS) and per-runtime-env worker processes
(worker_pool.h keys idle workers by runtime env hash). Redesigned lean:

 * packaging: working_dir / py_modules directories zip client-side and
   travel as ordinary objects through the cluster object plane (no
   separate package store); the daemon extracts into a content-addressed
   cache and reuses it across workers;
 * isolation: the daemon keys its idle-worker pool by the runtime env
   hash, so a worker only ever runs tasks of one runtime env (the
   reference's dedicated-worker semantics);
 * pip/conda are rejected loudly rather than silently ignored — this
   framework targets hermetic hosts (no network installs on TPU pods).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import tempfile
import threading
import zipfile
from typing import Any, Optional

_extract_lock = threading.Lock()
_hash_locks: dict[str, threading.Lock] = {}


def _lock_for(key: str) -> threading.Lock:
    with _extract_lock:
        return _hash_locks.setdefault(key, threading.Lock())

SUPPORTED_KEYS = {"env_vars", "working_dir", "py_modules"}
REJECTED_KEYS = {"pip", "conda", "container", "image_uri", "uv"}


def walk_dir(path: str):
    """os.walk with followlinks (a symlinked data/ subdir must ship, not
    silently vanish) plus cycle detection by (st_dev, st_ino) so a
    self-referential link can't recurse forever. Skips __pycache__."""
    seen: set = set()
    for root, dirs, files in os.walk(path, followlinks=True):
        try:
            st = os.stat(root)
        except OSError:
            continue
        key = (st.st_dev, st.st_ino)
        if key in seen:
            dirs[:] = []
            continue
        seen.add(key)
        dirs.sort()
        if "__pycache__" in dirs:
            dirs.remove("__pycache__")
        yield root, dirs, files


def _zip_dir(path: str) -> bytes:
    """Deterministic zip of a directory tree (stable hash across runs)."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, dirs, files in walk_dir(path):
            for f in sorted(files):
                full = os.path.join(root, f)
                rel = os.path.relpath(full, path)
                info = zipfile.ZipInfo(rel)  # fixed date: deterministic
                info.external_attr = (os.stat(full).st_mode & 0xFFFF) << 16
                with open(full, "rb") as fh:
                    z.writestr(info, fh.read())
    return buf.getvalue()


def validate_keys(runtime_env: dict) -> None:
    bad = set(runtime_env) & REJECTED_KEYS
    if bad:
        raise ValueError(
            f"runtime_env keys {sorted(bad)} are not supported on hermetic "
            "TPU hosts; bake dependencies into the image instead"
        )
    unknown = set(runtime_env) - SUPPORTED_KEYS
    if unknown:
        raise ValueError(f"unknown runtime_env keys: {sorted(unknown)}")


def package_runtime_env(runtime_env: Optional[dict], put) -> Optional[dict]:
    """Client side: validate, zip directories, stage zips via `put(bytes)
    -> object_id`. Returns the wire form of the runtime env (or None)."""
    if not runtime_env:
        return None
    validate_keys(runtime_env)
    wire: dict[str, Any] = {}
    env_vars = runtime_env.get("env_vars")
    if env_vars:
        wire["env_vars"] = {str(k): str(v) for k, v in env_vars.items()}
    wd = runtime_env.get("working_dir")
    if wd:
        if not os.path.isdir(wd):
            raise ValueError(f"working_dir {wd!r} is not a directory")
        data = _zip_dir(wd)
        wire["working_dir"] = {
            "object_id": put(data),
            "hash": hashlib.sha256(data).hexdigest()[:16],
        }
    mods = runtime_env.get("py_modules")
    if mods:
        entries = []
        for m in mods:
            if not os.path.isdir(m):
                raise ValueError(f"py_modules entry {m!r} is not a directory")
            data = _zip_dir(m)
            entries.append({
                "object_id": put(data),
                "hash": hashlib.sha256(data).hexdigest()[:16],
                "name": os.path.basename(os.path.normpath(m)),
            })
        wire["py_modules"] = entries
    return wire or None


def env_hash(wire: Optional[dict]) -> str:
    """Stable identity of a wire-form runtime env (worker-pool key)."""
    if not wire:
        return ""
    canon = json.dumps(
        {
            "env_vars": wire.get("env_vars", {}),
            "working_dir": wire.get("working_dir", {}).get("hash"),
            # name matters: identical bytes under different module names
            # materialize differently (the import-name symlink)
            "py_modules": [
                (m["name"], m["hash"]) for m in wire.get("py_modules", ())
            ],
        },
        sort_keys=True,
    )
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def materialize(wire: dict, fetch, cache_root: str,
                base_env: Optional[dict] = None) -> tuple[dict, Optional[str]]:
    """Daemon side: extract staged zips into the content-addressed cache.

    `fetch(object_id) -> bytes`; `base_env` is the worker's environment
    BEFORE runtime-env overlays (so an operator-supplied PYTHONPATH is
    prepended-to, not clobbered). Returns (extra_env_vars, workdir|None).
    Concurrent spawns of the same env serialize on a per-hash lock; the
    extraction staging dir is unique per attempt.
    """
    extra = dict(wire.get("env_vars", {}))
    paths: list[str] = []
    workdir = None

    def extract(entry) -> str:
        dest = os.path.join(cache_root, entry["hash"])
        with _lock_for(entry["hash"]):
            if not os.path.isdir(dest):
                data = fetch(entry["object_id"])
                if data is None:
                    raise RuntimeError(
                        f"runtime_env package {entry['hash']} unavailable"
                    )
                os.makedirs(cache_root, exist_ok=True)
                tmp = tempfile.mkdtemp(dir=cache_root, prefix=entry["hash"] + "-")
                with zipfile.ZipFile(io.BytesIO(data)) as z:
                    for info in z.infolist():
                        z.extract(info, tmp)
                        mode = info.external_attr >> 16
                        if mode:  # restore modes (extractall drops the x bit)
                            os.chmod(os.path.join(tmp, info.filename), mode)
                try:
                    os.replace(tmp, dest)
                except OSError:  # lost a cross-process race: dest exists
                    import shutil

                    shutil.rmtree(tmp, ignore_errors=True)
        return dest

    wd = wire.get("working_dir")
    if wd:
        workdir = extract(wd)
        paths.append(workdir)
    for m in wire.get("py_modules", ()):
        # a py_module dir is importable by its own name: put its PARENT on
        # the path, with the module dir linked under that name
        root = extract(m)
        named = os.path.join(root, "_mod", m["name"])
        with _lock_for(m["hash"]):
            if not os.path.islink(named) and not os.path.isdir(named):
                os.makedirs(os.path.dirname(named), exist_ok=True)
                try:
                    os.symlink(root, named)
                except FileExistsError:
                    pass
        paths.append(os.path.dirname(named))
    if paths:
        env = base_env if base_env is not None else os.environ
        existing = extra.get("PYTHONPATH", env.get("PYTHONPATH", ""))
        extra["PYTHONPATH"] = os.pathsep.join(
            paths + ([existing] if existing else [])
        )
    return extra, workdir
