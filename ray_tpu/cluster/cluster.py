"""Multi-process test/deployment cluster harness.

Reference analog: ray.cluster_utils.Cluster (python/ray/cluster_utils.py:135)
— but where round 1's cluster_utils registered capacity rows in an
in-process dict, this spawns a REAL GCS server process and one REAL node
daemon process per node; tasks execute inside worker processes on the
node that won the lease, and killing a node kills an OS process whose
death the GCS detects by heartbeat timeout.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Optional

from ray_tpu.cluster.client import ClusterClient
from ray_tpu.cluster.rpc import format_gcs_addr
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.cluster.cluster")


def _read_banner(proc: subprocess.Popen, tag: str, timeout: float = 30.0):
    """Read the '<TAG> host:port ...' line the child prints on startup."""
    result: list = []

    def read():
        assert proc.stdout is not None
        for line in proc.stdout:
            line = line.strip()
            if line.startswith(tag):
                result.append(line.split()[1:])
                break

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(timeout)
    if not result:
        proc.kill()
        raise RuntimeError(f"child did not print {tag} within {timeout}s")
    # keep draining stdout so the child never blocks on a full pipe
    threading.Thread(
        target=lambda: [None for _ in proc.stdout], daemon=True  # type: ignore[union-attr]
    ).start()
    return result[0]


class NodeProc:
    def __init__(self, proc: subprocess.Popen, node_id: str, addr: tuple):
        self.proc = proc
        self.node_id = node_id
        self.addr = addr

    def kill(self) -> None:
        """SIGKILL the daemon AND its workers (the whole node dies).

        A killed daemon can't unlink its tmpfs object-store file (graceful
        stop() does); sweep it here or crash-kill tests leak /dev/shm at
        ~hundreds of MB per run."""
        self._unlink_store()
        try:
            import signal

            os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                self.proc.kill()
            except Exception:
                pass

    def _unlink_store(self) -> None:
        from ray_tpu.utils.shm import shm_dir as _shm_dir

        shm_dir = _shm_dir()
        try:
            os.unlink(os.path.join(
                shm_dir, f"ray_tpu-store-{self.node_id}-{self.proc.pid}"
            ))
        except OSError:
            pass


class LocalCluster:
    """Spawn a GCS + N node-daemon processes on this machine."""

    def __init__(self, node_death_timeout_s: float = 2.0,
                 gcs_persist_path: Optional[str] = None,
                 standby: bool = False,
                 gcs_lease_timeout_s: float = 2.0):
        self._death_timeout = node_death_timeout_s
        self._persist_path = gcs_persist_path
        self._standby_requested = standby
        self._lease_timeout = gcs_lease_timeout_s
        self.gcs_proc: Optional[subprocess.Popen] = None
        self.gcs_addr: Optional[tuple] = None
        self.standby_proc: Optional[subprocess.Popen] = None
        self.standby_addr: Optional[tuple] = None
        self.nodes: dict[str, NodeProc] = {}
        self._client: Optional[ClusterClient] = None
        self._head: Optional[NodeProc] = None

    # -- lifecycle ------------------------------------------------------------

    def _spawn_gcs(self, port: int = 0) -> None:
        cmd = [
            sys.executable, "-m", "ray_tpu.cluster.gcs_service",
            "--death-timeout", str(self._death_timeout),
            "--port", str(port),
        ]
        if self._persist_path:
            cmd += ["--persist", self._persist_path]
        self.gcs_proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, text=True, env=self._child_env(),
            start_new_session=True,
        )
        host_port = _read_banner(self.gcs_proc, "GCS_ADDRESS")[0]
        host, port_s = host_port.rsplit(":", 1)
        self.gcs_addr = (host, int(port_s))

    def _spawn_standby(self) -> None:
        assert self.gcs_addr is not None, "spawn the primary first"
        cmd = [
            sys.executable, "-m", "ray_tpu.cluster.ha",
            "--primary", f"{self.gcs_addr[0]}:{self.gcs_addr[1]}",
            "--death-timeout", str(self._death_timeout),
            "--lease-timeout", str(self._lease_timeout),
            "--port", "0",
        ]
        if self._persist_path:
            cmd += ["--persist", self._persist_path + ".standby"]
        self.standby_proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, text=True, env=self._child_env(),
            start_new_session=True,
        )
        host_port = _read_banner(self.standby_proc, "GCS_ADDRESS")[0]
        host, port_s = host_port.rsplit(":", 1)
        self.standby_addr = (host, int(port_s))

    def start(self) -> "LocalCluster":
        self._spawn_gcs()
        if self._standby_requested:
            self._spawn_standby()
        return self

    @property
    def gcs_endpoints(self) -> tuple:
        """Ordered endpoint list for multi-endpoint clients: primary
        first, standby second (when deployed)."""
        assert self.gcs_addr is not None, "start() first"
        if self.standby_addr is not None:
            return (self.gcs_addr, self.standby_addr)
        return (self.gcs_addr,)

    def kill_gcs(self) -> None:
        """SIGKILL the control plane (FT testing)."""
        if self.gcs_proc is not None:
            try:
                import signal

                os.killpg(os.getpgid(self.gcs_proc.pid), signal.SIGKILL)
            except Exception:
                try:
                    self.gcs_proc.kill()
                except Exception:
                    pass
            self.gcs_proc = None

    def kill_gcs_primary(self) -> None:
        """SIGKILL the primary with NO restart (KILL_GCS_PRIMARY): the
        standby's lease expires and it promotes in place — the failover
        path, as opposed to restart_gcs's blackout-then-replay path."""
        assert self.standby_addr is not None, (
            "kill_gcs_primary requires standby=True"
        )
        self.kill_gcs()

    def restart_gcs(self) -> None:
        """Restart the GCS at the SAME address; with a persist path it
        replays actors/PGs/KV and nodes re-register via heartbeat
        (reference: Redis-backed GCS restart, gcs_init_data.cc)."""
        assert self.gcs_addr is not None, "start() first"
        self.kill_gcs()
        self._spawn_gcs(port=self.gcs_addr[1])

    def _child_env(self, extra: Optional[dict] = None) -> dict:
        env = dict(os.environ)
        # control-plane processes must never touch a TPU plugin
        env["JAX_PLATFORMS"] = "cpu"
        repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env.update(extra or {})
        return env

    def add_node(
        self,
        resources: Optional[dict] = None,
        node_id: Optional[str] = None,
        worker_env: Optional[dict] = None,
        object_capacity_bytes: Optional[int] = None,
        worker_rss_limit_mb: Optional[int] = None,
        memory_usage_threshold: Optional[float] = None,
        memory_monitor_interval_s: Optional[float] = None,
    ) -> NodeProc:
        assert self.gcs_addr is not None, "start() first"
        resources = resources or {"num_cpus": 1}
        res_s = ",".join(f"{k}={v}" for k, v in resources.items())
        cmd = [
            sys.executable, "-m", "ray_tpu.cluster.node_daemon",
            "--gcs", format_gcs_addr(self.gcs_endpoints),
            "--resources", res_s,
        ]
        if object_capacity_bytes is not None:
            cmd += ["--object-capacity", str(object_capacity_bytes)]
        if worker_rss_limit_mb is not None:
            cmd += ["--worker-rss-limit-mb", str(worker_rss_limit_mb)]
        # LocalCluster default: DISABLE the machine-wide pressure trigger
        # (dev/CI hosts are shared — an unrelated tenant pushing the box
        # past 95% must not make every test cluster kill its workers);
        # the production `ray start` CLI keeps the raylet-parity 0.95
        cmd += ["--memory-usage-threshold",
                str(1.0 if memory_usage_threshold is None
                    else memory_usage_threshold)]
        if memory_monitor_interval_s is not None:
            cmd += ["--memory-monitor-interval", str(memory_monitor_interval_s)]
        if node_id:
            cmd += ["--node-id", node_id]
        if worker_env:
            cmd += ["--worker-env", ",".join(f"{k}={v}" for k, v in worker_env.items())]
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, text=True, env=self._child_env(),
            start_new_session=True,
        )
        parts = _read_banner(proc, "NODE_ADDRESS")
        host, port = parts[0].rsplit(":", 1)
        node = NodeProc(proc, parts[1], (host, int(port)))
        self.nodes[node.node_id] = node
        if self._head is None:
            self._head = node
        return node

    @property
    def address(self) -> str:
        """GCS address for ray_tpu.init(address=...) — "h:p" or
        "h1:p1,h2:p2" when a standby is deployed."""
        assert self.gcs_addr is not None, "start() first"
        return format_gcs_addr(self.gcs_endpoints)

    def client(self) -> ClusterClient:
        if self._client is None:
            assert self.gcs_addr is not None and self._head is not None
            self._client = ClusterClient(self.gcs_endpoints, self._head.addr)
        return self._client

    def kill_node(self, node_id: str) -> None:
        node = self.nodes.pop(node_id, None)
        if node is not None:
            node.kill()

    def wait_for_nodes(self, n: int, timeout: float = 30.0) -> None:
        c = self.client()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            alive = [x for x in c.nodes() if x["alive"]]
            if len(alive) >= n:
                return
            time.sleep(0.05)
        raise TimeoutError(f"cluster did not reach {n} nodes")

    def wait_node_dead(self, node_id: str, timeout: float = 30.0) -> None:
        c = self.client()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for x in c.nodes():
                if x["node_id"] == node_id and not x["alive"]:
                    return
            time.sleep(0.05)
        raise TimeoutError(f"node {node_id} still alive after {timeout}s")

    def shutdown(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None
        for node in list(self.nodes.values()):
            node.kill()
        self.nodes.clear()
        for attr in ("gcs_proc", "standby_proc"):
            proc = getattr(self, attr)
            if proc is not None:
                try:
                    import signal

                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except Exception:
                    try:
                        proc.kill()
                    except Exception:
                        pass
                setattr(self, attr, None)

    def __enter__(self) -> "LocalCluster":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
