"""Cluster worker process: executes tasks and hosts actors.

Reference analog: the core-worker side of task execution
(src/ray/core_worker/core_worker.h:165 — TaskReceiver, direct
worker<->worker PushTask; actor scheduling queues in
src/ray/core_worker/transport/actor_task_submitter.h:75). Redesigned:
each worker is a spawned-clean Python process running one RPC server;
normal tasks run on an executor thread; actor calls serialize through a
per-actor FIFO asyncio lock (per-connection pipelining preserves caller
order, the lock preserves execution order — the reference's
ActorSchedulingQueue role).

Serialization: cloudpickle with persistent ids — ObjectRefs travel as
("objref", id) and are materialized through the node daemon's fetch
path on the executing side (the reference inlines resolved values via
the plasma provider; here the daemon is the provider).
"""

from __future__ import annotations

import argparse
import asyncio
import io
import threading
import traceback
from typing import Any, Optional

import cloudpickle

from ray_tpu.cluster.rpc import RpcClient, RpcServer, parse_gcs_addr
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.cluster.worker")


from ray_tpu.cluster.serialization import (  # noqa: E402
    _ErrorValue,
    dumps_value,
    loads_value,
)


def _framework_actor_method(actor, name: str):
    """Framework-injected actor methods for PROCESS actors (the in-process
    twin is actor_runtime._framework_method): gang/DAG setup calls the
    driver fires at every member before user traffic."""
    if name == "__ray_tpu_collective_init__":
        from ray_tpu.collective.collective import init_collective_group

        return lambda world, rank, backend, group, gen=0: init_collective_group(
            world, rank, backend=backend, group_name=group, gen=gen
        )
    if name == "__ray_tpu_dag_exec_loop__":
        from ray_tpu.dag.compiled import _actor_exec_loop

        return lambda plan, input_source: _actor_exec_loop(
            actor, plan, input_source
        )
    return None


class WorkerRuntime:
    def __init__(self, daemon_addr: tuple, worker_id: str,
                 gcs_addr: Optional[tuple] = None):
        self.worker_id = worker_id
        self.daemon_addr = tuple(daemon_addr)
        self.gcs_addr = tuple(gcs_addr) if gcs_addr else None
        self.daemon = RpcClient(*daemon_addr, timeout=120.0).connect(retries=20)
        self.node_id: Optional[str] = None
        self.shm = None  # attached after registration (daemon owns the file)
        self.actors: dict[bytes, Any] = {}
        self._actor_locks: dict[bytes, asyncio.Lock] = {}
        # registration metadata per hosted actor (name/namespace/
        # max_restarts/creation_spec...) — the data-plane ground truth a
        # reconciling GCS rebuilds its actor table from after a restart
        # with a stale or lost snapshot (rpc_actor_inventory)
        self._actor_meta: dict[bytes, dict] = {}
        self.rpc = RpcServer(self)
        # execution-side tracing: spans buffered here, flushed to the node
        # daemon in batches off the hot path (reference: per-worker
        # ProfileEvents batched to the GCS task-event pipeline,
        # core_worker/task_event_buffer.h)
        from collections import deque

        self._spans: "deque[dict]" = deque(maxlen=4096)
        self._span_flusher = threading.Thread(
            target=self._flush_spans_loop, name="span-flush", daemon=True
        )
        self._span_flusher.start()

    def _flush_spans_loop(self) -> None:
        import time as _time

        while True:
            _time.sleep(0.5)
            if not self._spans:
                continue
            batch = []
            while self._spans and len(batch) < 512:
                batch.append(self._spans.popleft())
            try:
                self.daemon.call("record_spans", {"spans": batch}, timeout=10)
            except Exception:  # noqa: BLE001 — tracing must never hurt tasks
                pass

    # -- object plumbing ------------------------------------------------------
    # Same-node objects ride the shared-memory store (plasma-equivalent):
    # reads hit the mapping directly, returns are sealed in place and only
    # the 16-byte id crosses the RPC (reference: plasma client over the
    # raylet's in-process store). RPC paths remain the fallback.

    def resolve_ref(self, object_id: bytes) -> Any:
        data = None
        if self.shm is not None:
            try:
                data = self.shm.get_bytes(object_id)
            except OSError:
                data = None
        if data is None:
            data = self.daemon.call(
                "fetch_object", {"object_id": object_id}, timeout=60
            )
        if data is None:
            raise RuntimeError(f"object {object_id.hex()} unavailable")
        value = loads_value(data, self.resolve_ref)
        if isinstance(value, _ErrorValue):
            raise RuntimeError(
                f"dependency failed: {value.task_desc}: {value.exc!r}"
            )
        return value

    SHM_MIN_BYTES = 64 << 10  # small returns: one RPC beats the shm protocol

    def put_return(self, object_id: bytes, value: Any) -> None:
        data = dumps_value(value)
        if (
            self.shm is not None
            and len(data) >= self.SHM_MIN_BYTES
            and self.shm.put_pinned(object_id, data)
        ):
            try:
                r = self.daemon.call(
                    "object_sealed", {"object_id": object_id}, timeout=60
                )
            finally:
                # drop the creator ref only after the daemon pinned it
                # (no zero-ref window for the LRU to evict through)
                try:
                    self.shm.release(object_id)
                except OSError:
                    pass
            if r.get("ok"):
                return
            try:  # daemon would not adopt: reclaim and fall back
                self.shm.force_delete(object_id)
            except OSError:
                pass
        self.daemon.call(
            "put_object",
            {"object_id": object_id, "data": data},
            timeout=60,
        )

    # -- task execution -------------------------------------------------------

    @staticmethod
    def _trace_ids(tctx) -> dict:
        """Span-dict fields for the attached trace context (empty when
        the envelope carried no trace)."""
        if tctx is None:
            return {}
        return {"trace_id": tctx.trace_id, "span_id": tctx.span_id}

    def _execute(self, payload) -> dict:
        import time as _time

        from ray_tpu.obs import context as trace_context

        desc = payload.get("desc", "task")
        return_ids = payload["return_ids"]
        t0 = _time.time()
        # restore the envelope's trace so task code and nested submits on
        # this worker stay in the caller's trace
        with trace_context.use_from(payload.get("trace")) as tctx:
            trace_ids = self._trace_ids(tctx)
            try:
                func = cloudpickle.loads(payload["func"])
                args, kwargs = loads_value(payload["args"], self.resolve_ref)
                result = func(*args, **kwargs)
                self._store_returns(return_ids, result, payload.get("num_returns", 1))
                self._spans.append({
                    "desc": desc, "task_id": payload.get("task_id", b"").hex(),
                    "worker_id": self.worker_id, "start": t0, "end": _time.time(),
                    "ok": True, **trace_ids,
                })
                return {"ok": True}
            except BaseException as e:  # noqa: BLE001
                tb = traceback.format_exc()
                err = _ErrorValue(e, tb, desc)
                for rid in return_ids:
                    try:
                        self.put_return(rid, err)
                    except Exception:
                        pass
                self._spans.append({
                    "desc": desc, "task_id": payload.get("task_id", b"").hex(),
                    "worker_id": self.worker_id, "start": t0, "end": _time.time(),
                    "ok": False, **trace_ids,
                })
                return {"ok": False, "error": repr(e), "tb": tb,
                        "retryable": not isinstance(e, (SystemExit,))}

    def _store_returns(self, return_ids, result, num_returns: int) -> None:
        if num_returns == 1:
            self.put_return(return_ids[0], result)
            return
        if not isinstance(result, (tuple, list)) or len(result) != num_returns:
            raise ValueError(
                f"task declared num_returns={num_returns} but returned "
                f"{type(result).__name__}"
            )
        for rid, val in zip(return_ids, result):
            self.put_return(rid, val)

    async def rpc_push_task(self, payload, peer):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self._execute, payload)

    # -- actors ---------------------------------------------------------------

    async def rpc_create_actor(self, payload, peer):
        loop = asyncio.get_running_loop()

        def _create():
            try:
                cls, args, kwargs = loads_value(
                    payload["creation_spec"], self.resolve_ref
                )
                self.actors[payload["actor_id"]] = cls(*args, **kwargs)
                meta = dict(payload.get("meta") or {})
                meta["creation_spec"] = payload["creation_spec"]
                self._actor_meta[payload["actor_id"]] = meta
                return {"ok": True}
            except BaseException as e:  # noqa: BLE001
                return {"ok": False, "error": repr(e), "tb": traceback.format_exc()}

        self._actor_locks.setdefault(payload["actor_id"], asyncio.Lock())
        return await loop.run_in_executor(None, _create)

    async def rpc_actor_call(self, payload, peer):
        actor_id = payload["actor_id"]
        actor = self.actors.get(actor_id)
        if actor is None:
            return {"ok": False, "error": f"actor {actor_id.hex()} not here",
                    "actor_missing": True}
        lock = self._actor_locks.setdefault(actor_id, asyncio.Lock())
        loop = asyncio.get_running_loop()

        def _invoke():
            method = _framework_actor_method(actor, payload["method"]) or getattr(
                actor, payload["method"]
            )
            args, kwargs = loads_value(payload["args"], self.resolve_ref)
            result = method(*args, **kwargs)
            if asyncio.iscoroutine(result):
                result = asyncio.run(result)
            return result

        desc = f"{type(actor).__name__}.{payload['method']}"
        import time as _time

        from ray_tpu.obs import context as trace_context

        t0 = _time.time()
        with trace_context.use_from(payload.get("trace")) as tctx:
            trace_ids = self._trace_ids(tctx)
            try:
                # only METHOD EXECUTION needs the FIFO lock (per-caller
                # order); storing the result is an independent RPC to the
                # daemon and serializing it under the lock would cap the
                # actor's call rate at the store round-trip
                import contextvars as _cv

                # run_in_executor does not propagate contextvars: ship the
                # coroutine's context (with the attached trace) to the pool
                call_ctx = _cv.copy_context()
                async with lock:
                    result = await loop.run_in_executor(None, call_ctx.run, _invoke)
                await loop.run_in_executor(
                    None,
                    self._store_returns,
                    payload["return_ids"], result, payload.get("num_returns", 1),
                )
                # span only after the returns landed: a store failure takes
                # the except path and must record ONE ok=False span, not both
                self._spans.append({
                    "desc": desc, "worker_id": self.worker_id,
                    "actor_id": actor_id.hex(), "start": t0, "end": _time.time(),
                    "ok": True, **trace_ids,
                })
                return {"ok": True}
            except BaseException as e:  # noqa: BLE001
                tb = traceback.format_exc()
                err = _ErrorValue(e, tb, desc)
                for rid in payload["return_ids"]:
                    try:
                        self.put_return(rid, err)
                    except Exception:
                        pass
                self._spans.append({
                    "desc": desc, "worker_id": self.worker_id,
                    "actor_id": actor_id.hex(), "start": t0, "end": _time.time(),
                    "ok": False, **trace_ids,
                })
                return {"ok": False, "error": repr(e), "tb": tb}

    async def rpc_destroy_actor(self, payload, peer):
        self.actors.pop(payload["actor_id"], None)
        self._actor_locks.pop(payload["actor_id"], None)
        self._actor_meta.pop(payload["actor_id"], None)
        return {"ok": True}

    def rpc_actor_inventory(self, payload, peer):
        """Live actors hosted here, with their registration metadata —
        the node daemon forwards this in its reconcile report when a
        restarted GCS asks it to re-register."""
        out = []
        for aid in list(self.actors):
            meta = self._actor_meta.get(aid, {})
            out.append({"actor_id": aid, **meta})
        return out

    def rpc_ping(self, payload, peer):
        return {"worker_id": self.worker_id, "actors": len(self.actors)}

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        addr = self.rpc.start()
        # install the ambient ClusterClient BEFORE registering: the moment
        # the daemon processes register_worker it may grant a lease and a
        # submitter may push a task carrying ObjectRefs/actor handles —
        # their rebuild path needs the ambient client already in place
        if self.gcs_addr is not None:
            from ray_tpu.cluster.client import ClusterClient
            from ray_tpu.core import api
            from ray_tpu.core.cluster_backend import ClusterBackend

            client = ClusterClient(self.gcs_addr, self.daemon_addr)
            client.auto_free = False  # workers borrow; drivers own/free
            # nested api calls (tasks submitting tasks, actors creating
            # actors) ride the same cluster, not a private in-process
            # runtime (reference: workers share the driver's GCS plane)
            api._CLUSTER[0] = ClusterBackend.from_client(client)
        r = self.daemon.call(
            "register_worker", {"worker_id": self.worker_id, "addr": addr}
        )
        self.node_id = r.get("node_id")
        if r.get("shm_path"):
            try:
                from ray_tpu.native.shm import ShmObjectStore

                self.shm = ShmObjectStore.open(r["shm_path"])
            except Exception:
                logger.warning("shm store unavailable; using RPC object path")
        if self.gcs_addr is None and r.get("gcs_addr") and r.get("daemon_addr"):
            # legacy fallback (daemon didn't pass --gcs): install late
            from ray_tpu.cluster.client import ClusterClient

            ClusterClient(tuple(r["gcs_addr"]), tuple(r["daemon_addr"]))
        logger.info("worker %s serving at %s (node %s)",
                    self.worker_id, addr, self.node_id)


def _pin_jax_platform() -> None:
    """Honor an explicit non-TPU JAX_PLATFORMS before any user code runs.

    Some environments force-register a TPU plugin in every process
    (sitecustomize); the env var alone does not stop its backend init,
    and a wedged TPU tunnel then hangs the first jax touch forever.
    Pinning via jax.config is the only reliable opt-out."""
    import os

    want = os.environ.get("JAX_PLATFORMS", "")
    if want and "tpu" not in want and "axon" not in want:
        try:
            import jax

            jax.config.update("jax_platforms", want)
        except Exception:
            pass


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--daemon", required=True)
    p.add_argument("--worker-id", required=True)
    p.add_argument("--gcs", default=None)
    args = p.parse_args()
    _pin_jax_platform()
    from ray_tpu.chaos import harness as _chaos

    _chaos.install_from_env()  # adopt a driver-propagated fault schedule
    host, port = args.daemon.rsplit(":", 1)
    gcs = None
    if args.gcs:
        gcs = parse_gcs_addr(args.gcs)  # "h:p" or HA pair "h1:p1,h2:p2"
    rt = WorkerRuntime((host, int(port)), args.worker_id, gcs_addr=gcs)
    rt.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
