"""Per-node daemon: worker pool, lease protocol, object service.

Reference analog: the raylet (src/ray/raylet/node_manager.h:118 —
worker-lease handling at node_manager.cc:1915 HandleRequestWorkerLease,
WorkerPool worker_pool.h:125, object transfer via
src/ray/object_manager/object_manager.h:117). Redesigned:

 * leases: a submitter asks its local daemon for a worker; the daemon
   grants a dedicated worker process if the resources fit, otherwise
   answers with a spillback target chosen from the GCS resource view
   (the hybrid policy's "prefer local, spill to the best-fitting remote"
   leg, hybrid_scheduling_policy.h:29-49);
 * workers: real OS processes (spawned clean — no fork-after-JAX),
   each with its own RPC server for direct submitter->worker pushes;
 * objects: a per-node in-memory store; `fetch` pulls missing objects
   chunk-wise from a holder found via the GCS object directory and
   caches them locally (PullManager/PushManager collapsed into one
   chunked pull path);
 * placement-group bundles: reservations carve sub-pools out of the
   node's availability, keyed (pg_id, bundle_index).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import threading
import time
import uuid
from typing import Any, Optional

from ray_tpu.cluster.rpc import ClientPool, RemoteError, RpcClient, RpcError, RpcServer
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.cluster.node")

CHUNK = 4 << 20  # object transfer chunk size


class ObjectService:
    """Node-local object table + chunked cross-node pull."""

    def __init__(self, node_id: str, gcs: RpcClient, pool: ClientPool):
        self._objects: dict[bytes, bytes] = {}
        self._lock = threading.Lock()
        self._node_id = node_id
        self._gcs = gcs
        self._pool = pool

    def put(self, object_id: bytes, data: bytes) -> None:
        with self._lock:
            self._objects[object_id] = data
        self._gcs.call(
            "add_object_location",
            {"object_id": object_id, "node_id": self._node_id},
        )

    def get_local(self, object_id: bytes) -> Optional[bytes]:
        with self._lock:
            return self._objects.get(object_id)

    def free(self, object_id: bytes) -> None:
        with self._lock:
            self._objects.pop(object_id, None)
        try:
            self._gcs.call(
                "remove_object_location",
                {"object_id": object_id, "node_id": self._node_id},
            )
        except RpcError:
            pass

    def fetch(self, object_id: bytes, timeout: float = 30.0) -> Optional[bytes]:
        """Local hit or remote pull (chunked); caches + registers locally."""
        data = self.get_local(object_id)
        if data is not None:
            return data
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            addrs = self._gcs.call("locate_object", {"object_id": object_id})
            for addr in addrs:
                if tuple(addr) == self._pool_self_addr:
                    continue
                try:
                    data = self._pull_from(tuple(addr), object_id)
                except (RpcError, RemoteError):
                    continue
                if data is not None:
                    self.put(object_id, data)
                    return data
            time.sleep(0.05)
        return None

    _pool_self_addr: tuple = ("", 0)  # set by daemon after bind

    def _pull_from(self, addr: tuple, object_id: bytes) -> Optional[bytes]:
        c = self._pool.get(addr)
        meta = c.call("object_meta", {"object_id": object_id})
        if meta is None:
            return None
        size = meta["size"]
        parts = []
        off = 0
        while off < size:
            chunk = c.call(
                "object_chunk",
                {"object_id": object_id, "offset": off, "length": CHUNK},
            )
            if chunk is None:
                return None
            parts.append(chunk)
            off += len(chunk)
        return b"".join(parts)

    def stats(self) -> dict:
        with self._lock:
            return {
                "num_objects": len(self._objects),
                "bytes": sum(len(v) for v in self._objects.values()),
            }


class WorkerHandle:
    def __init__(self, proc: subprocess.Popen, worker_id: str):
        self.proc = proc
        self.worker_id = worker_id
        self.addr: Optional[tuple] = None
        self.ready = threading.Event()

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        try:
            self.proc.kill()
        except Exception:
            pass


class NodeDaemon:
    """The per-node control process (raylet-equivalent)."""

    def __init__(
        self,
        gcs_addr: tuple,
        resources: dict,
        node_id: Optional[str] = None,
        host: str = "127.0.0.1",
        labels: Optional[dict] = None,
        worker_env: Optional[dict] = None,
        heartbeat_interval_s: float = 0.5,
    ):
        self.node_id = node_id or f"node-{uuid.uuid4().hex[:8]}"
        self.gcs_addr = gcs_addr
        self.total = dict(resources)
        self.available = dict(resources)
        self.labels = labels or {}
        self.worker_env = worker_env or {}
        self._hb_interval = heartbeat_interval_s
        # RLock: PG-bundle reserve is check-then-act over _bundles AND the
        # node availability — the whole sequence must be atomic across
        # handler threads (reference: PlacementGroupResourceManager commits
        # bundle resources atomically)
        self._res_lock = threading.RLock()
        self._leases: dict[str, dict] = {}  # lease_id -> {resources, worker}
        self._bundles: dict[tuple, dict] = {}  # (pg_id, idx) -> reserved resources
        self._idle_workers: list[WorkerHandle] = []
        self._all_workers: dict[str, WorkerHandle] = {}
        self._wlock = threading.Lock()
        self.rpc = RpcServer(self, host=host)
        self.pool = ClientPool()
        self.gcs = RpcClient(*gcs_addr).connect(retries=20)
        self.objects = ObjectService(self.node_id, self.gcs, self.pool)
        self._stop = threading.Event()
        self.addr: Optional[tuple] = None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> tuple:
        self.addr = self.rpc.start()
        self.objects._pool_self_addr = self.addr
        self.gcs.call(
            "register_node",
            {
                "node_id": self.node_id,
                "addr": self.addr,
                "resources": self.total,
                "labels": self.labels,
            },
        )
        t = threading.Thread(target=self._heartbeat_loop, name="node-hb", daemon=True)
        t.start()
        return self.addr

    def stop(self) -> None:
        self._stop.set()
        with self._wlock:
            for w in self._all_workers.values():
                w.kill()
            self._all_workers.clear()
            self._idle_workers.clear()
        try:
            self.gcs.call("drain_node", {"node_id": self.node_id}, timeout=2)
        except (RpcError, RemoteError):
            pass
        self.rpc.stop()
        self.gcs.close()
        self.pool.close_all()

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self._hb_interval):
            try:
                with self._res_lock:
                    avail = dict(self.available)
                r = self.gcs.call(
                    "heartbeat",
                    {"node_id": self.node_id, "available": avail},
                    timeout=5,
                )
                if not r.get("ok") and r.get("reregister"):
                    self.gcs.call(
                        "register_node",
                        {
                            "node_id": self.node_id,
                            "addr": self.addr,
                            "resources": self.total,
                            "labels": self.labels,
                        },
                    )
            except (RpcError, RemoteError):
                pass  # GCS down: keep trying (it may restart)

    # -- resources ------------------------------------------------------------

    def _try_acquire(self, res: dict, pool: Optional[dict] = None) -> bool:
        with self._res_lock:
            target = pool if pool is not None else self.available
            if all(target.get(k, 0.0) >= v - 1e-9 for k, v in res.items()):
                for k, v in res.items():
                    target[k] = target.get(k, 0.0) - v
                return True
            return False

    def _release(self, res: dict, pool: Optional[dict] = None) -> None:
        with self._res_lock:
            target = pool if pool is not None else self.available
            for k, v in res.items():
                target[k] = target.get(k, 0.0) + v

    # -- worker pool ----------------------------------------------------------

    def _spawn_worker(self) -> WorkerHandle:
        worker_id = f"w-{uuid.uuid4().hex[:8]}"
        env = dict(os.environ)
        env.update(self.worker_env)
        env["RAY_TPU_WORKER_ID"] = worker_id
        env["RAY_TPU_NODE_ID"] = self.node_id
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "ray_tpu.cluster.worker_main",
                "--daemon", f"{self.addr[0]}:{self.addr[1]}",
                "--worker-id", worker_id,
                "--gcs", f"{self.gcs_addr[0]}:{self.gcs_addr[1]}",
            ],
            env=env,
            cwd=os.getcwd(),
        )
        h = WorkerHandle(proc, worker_id)
        with self._wlock:
            self._all_workers[worker_id] = h
        return h

    def _lease_worker(self) -> WorkerHandle:
        with self._wlock:
            while self._idle_workers:
                w = self._idle_workers.pop()
                if w.alive():
                    return w
        w = self._spawn_worker()
        if not w.ready.wait(timeout=60):
            w.kill()
            raise RpcError("worker failed to start in 60s")
        return w

    def rpc_register_worker(self, payload, peer):
        with self._wlock:
            w = self._all_workers.get(payload["worker_id"])
        if w is None:
            return {"ok": False}
        w.addr = tuple(payload["addr"])
        w.ready.set()
        return {
            "ok": True,
            "node_id": self.node_id,
            "gcs_addr": self.gcs_addr,
            "daemon_addr": self.addr,
        }

    # -- lease protocol -------------------------------------------------------

    def rpc_request_worker_lease(self, payload, peer):
        """Grant a local worker or answer with a spillback target.

        payload: {resources, pg_id?, bundle_index?, exclude?: [node_id]}
        """
        res = payload.get("resources", {})
        pg_key = None
        if payload.get("pg_id") is not None:
            pg_key = (payload["pg_id"], payload.get("bundle_index", 0))
            with self._res_lock:
                bundle_pool = self._bundles.get(pg_key)
                if bundle_pool is None:
                    return {"error": f"no bundle reserved here for {pg_key}"}
                acquired = self._try_acquire(res, bundle_pool)
        else:
            acquired = self._try_acquire(res)
        if acquired:
            try:
                w = self._lease_worker()
            except RpcError as e:
                self._release(res, self._bundles.get(pg_key) if pg_key else None)
                return {"error": str(e)}
            lease_id = uuid.uuid4().hex
            self._leases[lease_id] = {
                "resources": res, "worker": w, "pg_key": pg_key,
            }
            return {
                "grant": {
                    "lease_id": lease_id,
                    "worker_addr": w.addr,
                    "worker_id": w.worker_id,
                    "node_id": self.node_id,
                    # the address release_lease must go to — without it a
                    # remote actor's lease could only ever be released at
                    # the driver's local daemon (leaking worker+resources)
                    "node_addr": self.addr,
                }
            }
        # spillback: consult the GCS view for a node that fits
        if pg_key is not None:
            return {"retry_after": 0.05}  # bundle is busy; wait for release
        if payload.get("pinned"):
            # hard node affinity: the caller can't use a spillback target,
            # so don't compute one; tell it to back off instead
            return {"retry_after": 0.2, "node_id": self.node_id}
        exclude = set(payload.get("exclude", ())) | {self.node_id}
        try:
            nodes = self.gcs.call("list_nodes", None, timeout=5)
        except (RpcError, RemoteError):
            nodes = []
        candidates = [
            n for n in nodes
            if n["alive"] and n["node_id"] not in exclude
            and all(n["available"].get(k, 0.0) >= v for k, v in res.items())
        ]
        if candidates:
            # hybrid policy's remote leg: random among the top-k by
            # availability, so concurrent submitters with the same (stale)
            # view don't all herd onto one node
            # (reference: hybrid_scheduling_policy.h:29-49)
            import random

            key = next(iter(res), None)
            random.shuffle(candidates)
            candidates.sort(
                key=lambda n: -n["available"].get(key, 0.0) if key else 0.0
            )
            top_k = candidates[: max(1, min(3, len(candidates)))]
            pick = random.choice(top_k)
            return {"spillback": pick["addr"],
                    "spillback_node": pick["node_id"],
                    "node_id": self.node_id}
        return {"retry_after": 0.05, "node_id": self.node_id}

    def rpc_release_lease(self, payload, peer):
        lease = self._leases.pop(payload["lease_id"], None)
        if lease is None:
            return {"ok": False}
        with self._res_lock:
            pool = self._bundles.get(lease["pg_key"]) if lease["pg_key"] else None
            self._release(lease["resources"], pool)
        w: WorkerHandle = lease["worker"]
        if payload.get("kill") or not w.alive():
            w.kill()
            with self._wlock:
                self._all_workers.pop(w.worker_id, None)
        else:
            with self._wlock:
                self._idle_workers.append(w)
        return {"ok": True}

    # -- placement group bundles ----------------------------------------------

    def rpc_reserve_pg_bundle(self, payload, peer):
        key = (payload["pg_id"], payload["bundle_index"])
        res = payload["resources"]
        with self._res_lock:  # atomic check-then-reserve across handlers
            if key in self._bundles:
                return {"ok": True}  # idempotent
            if not self._try_acquire(res):
                return {"ok": False, "error": "insufficient resources"}
            self._bundles[key] = dict(res)
        return {"ok": True}

    def rpc_release_pg_bundle(self, payload, peer):
        key = (payload["pg_id"], payload["bundle_index"])
        with self._res_lock:
            pool = self._bundles.pop(key, None)
            if pool is None:
                return {"ok": False}
            # return whatever is still reserved plus whatever tasks gave back
            self._release(pool)
        return {"ok": True}

    def rpc_release_pg_all(self, payload, peer):
        pg_id = payload["pg_id"]
        with self._res_lock:
            for key in [k for k in self._bundles if k[0] == pg_id]:
                self._release(self._bundles.pop(key))
        return {"ok": True}

    # -- object service -------------------------------------------------------

    def rpc_put_object(self, payload, peer):
        self.objects.put(payload["object_id"], payload["data"])
        return {"ok": True}

    def rpc_object_meta(self, payload, peer):
        data = self.objects.get_local(payload["object_id"])
        return None if data is None else {"size": len(data)}

    def rpc_object_chunk(self, payload, peer):
        data = self.objects.get_local(payload["object_id"])
        if data is None:
            return None
        off = payload["offset"]
        return data[off : off + payload["length"]]

    def rpc_fetch_object(self, payload, peer):
        """Blocking local-or-remote fetch (driver/worker `get` path)."""
        return self.objects.fetch(
            payload["object_id"], timeout=payload.get("timeout", 30.0)
        )

    def rpc_has_object(self, payload, peer):
        return self.objects.get_local(payload["object_id"]) is not None

    def rpc_free_object(self, payload, peer):
        self.objects.free(payload["object_id"])
        return {"ok": True}

    # -- misc -----------------------------------------------------------------

    def rpc_ping(self, payload, peer):
        return {"node_id": self.node_id}

    def rpc_stats(self, payload, peer):
        with self._res_lock:
            return {
                "node_id": self.node_id,
                "total": dict(self.total),
                "available": dict(self.available),
                "num_leases": len(self._leases),
                "num_workers": len(self._all_workers),
                "objects": self.objects.stats(),
            }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--gcs", required=True)
    p.add_argument("--node-id", default=None)
    p.add_argument("--resources", default="num_cpus=1")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--worker-env", default="", help="k=v,... for worker processes")
    args = p.parse_args()
    host, port = args.gcs.rsplit(":", 1)
    resources: dict[str, float] = {}
    for kv in args.resources.split(","):
        if kv:
            k, v = kv.split("=")
            resources[k] = float(v)
    worker_env: dict[str, str] = {}
    for kv in args.worker_env.split(","):
        if kv:
            k, v = kv.split("=", 1)
            worker_env[k] = v
    daemon = NodeDaemon(
        (host, int(port)), resources, node_id=args.node_id, worker_env=worker_env
    )
    addr = daemon.start()
    print(f"NODE_ADDRESS {addr[0]}:{addr[1]} {daemon.node_id}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        daemon.stop()


if __name__ == "__main__":
    main()
