"""Per-node daemon: worker pool, lease protocol, object service.

Reference analog: the raylet (src/ray/raylet/node_manager.h:118 —
worker-lease handling at node_manager.cc:1915 HandleRequestWorkerLease,
WorkerPool worker_pool.h:125, object transfer via
src/ray/object_manager/object_manager.h:117). Redesigned:

 * leases: a submitter asks its local daemon for a worker; the daemon
   grants a dedicated worker process if the resources fit, otherwise
   answers with a spillback target chosen from the GCS resource view
   (the hybrid policy's "prefer local, spill to the best-fitting remote"
   leg, hybrid_scheduling_policy.h:29-49);
 * workers: real OS processes (spawned clean — no fork-after-JAX),
   each with its own RPC server for direct submitter->worker pushes;
 * objects: a per-node in-memory store; `fetch` pulls missing objects
   chunk-wise from a holder found via the GCS object directory and
   caches them locally (PullManager/PushManager collapsed into one
   chunked pull path);
 * placement-group bundles: reservations carve sub-pools out of the
   node's availability, keyed (pg_id, bundle_index).
"""

from __future__ import annotations

import argparse
import asyncio
import os
import queue as queue_mod
import subprocess
import sys
import threading
import time
import uuid
from typing import Any, Optional

from ray_tpu.chaos import harness as _chaos
from ray_tpu.cluster.rpc import (
    ClientPool,
    ReconnectingRpcClient,
    RemoteError,
    RpcClient,
    RpcError,
    RpcServer,
    format_gcs_addr,
    parse_gcs_addr,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.cluster.node")

CHUNK = 4 << 20  # object transfer chunk size


def _node_gauges() -> dict:
    """Per-node utilization gauges (tagged by node so in-process test
    daemons sharing one registry stay distinguishable). Aggregation kinds
    ride telemetry snapshots to the GCS (obs/telemetry.py)."""
    from ray_tpu.obs.telemetry import cluster_gauge

    return {
        "workers": cluster_gauge(
            "node_workers",
            description="worker processes attached to this node daemon",
            tag_keys=("node",),
        ),
        "leases": cluster_gauge(
            "node_leases",
            description="worker leases currently granted on this node",
            tag_keys=("node",),
        ),
        "queued_leases": cluster_gauge(
            "node_queued_leases",
            description="lease requests parked in this node's grant queue "
            "(the autoscaler's per-node demand signal)",
            tag_keys=("node",),
        ),
        "object_bytes": cluster_gauge(
            "node_object_store_bytes",
            description="bytes resident in this node's object-store memory "
            "tier (dict tier; shm tier reports via stats())",
            tag_keys=("node",),
        ),
        "oom_kills": cluster_gauge(
            "node_oom_kills",
            description="workers killed by this node's memory monitor "
            "since daemon start",
            tag_keys=("node",),
        ),
    }


def register_metrics() -> None:
    """scripts/check_metrics.py hook: force node gauges to register."""
    _node_gauges()


class ObjectService:
    """Node-local object table: byte-capped LRU memory tier + disk-spill
    tier + chunked cross-node pull.

    Reference analog: the plasma store's LRU eviction
    (src/ray/object_manager/plasma/eviction_policy.h:105) combined with
    the raylet's spill-to-disk path (raylet/local_object_manager.h:41).
    Objects never silently vanish: over-capacity entries spill to the
    node's spill dir and reload on access; only `free` deletes."""

    def __init__(self, node_id: str, gcs: RpcClient, pool: ClientPool,
                 capacity_bytes: int = 512 << 20,
                 spill_dir: Optional[str] = None,
                 shm_path: Optional[str] = None):
        from collections import OrderedDict

        self._objects: "OrderedDict[bytes, bytes]" = OrderedDict()
        self._bytes = 0
        self._capacity = capacity_bytes
        # shared-memory primary tier (the C++ plasma-equivalent,
        # native/src/shm_store.cc): workers on this node read results
        # zero-RPC and write returns without shipping bytes through the
        # daemon. The daemon PINS every adopted object (holds a ref) so
        # the store's zero-ref LRU eviction can never drop a primary copy;
        # shm-full falls back to the Python dict tier + disk spill.
        self._shm = None
        self._shm_held: set[bytes] = set()
        self.shm_path = None
        if shm_path:
            try:
                from ray_tpu.native.shm import ShmObjectStore

                self._shm = ShmObjectStore.create(shm_path, capacity_bytes)
                self.shm_path = shm_path
                # ONE memory budget: shm takes it, the dict tier becomes a
                # small overflow buffer (not a second full-size cache)
                self._capacity = max(
                    capacity_bytes // 4, min(capacity_bytes, 16 << 20)
                )
            except Exception:
                logger.exception("shm store unavailable; using dict tier only")
        self._spill_dir = spill_dir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), f"ray_tpu-spill-{node_id}"
        )
        self._spilled: set[bytes] = set()
        self._lock = threading.Lock()
        self._arrived = threading.Condition(self._lock)  # wakes fetch waiters
        self._node_id = node_id
        self._gcs = gcs
        self._pool = pool
        # object-directory announcements that failed because the GCS was
        # dark: the object is stored and served locally regardless (a
        # control-plane outage must not fail the data plane's put path);
        # the heartbeat loop re-announces these once the GCS answers
        self._unannounced: set[bytes] = set()

    def _spill_path(self, object_id: bytes) -> str:
        return os.path.join(self._spill_dir, object_id.hex())

    def _evict_over_capacity_locked(self) -> None:
        """Spill least-recently-used entries until under the byte cap."""
        while self._bytes > self._capacity and len(self._objects) > 1:
            oid, data = self._objects.popitem(last=False)  # LRU end
            self._bytes -= len(data)
            try:
                os.makedirs(self._spill_dir, exist_ok=True)
                tmp = self._spill_path(oid) + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, self._spill_path(oid))
                self._spilled.add(oid)
            except OSError:
                # disk full/unwritable: keep it in memory rather than lose it
                self._objects[oid] = data
                self._bytes += len(data)
                logger.exception("spill failed; keeping %s in memory", oid.hex()[:12])
                return

    # objects below this ride the dict tier: for tiny payloads the shm
    # alloc/seal/ref protocol costs more than the bytes it saves
    SHM_MIN_BYTES = 64 << 10

    def _shm_put_pinned(self, object_id: bytes, data: bytes) -> bool:
        """Store into shm holding the creator ref (pin). False on full."""
        if self._shm is None or len(data) < self.SHM_MIN_BYTES:
            return False
        if not self._shm.put_pinned(object_id, data):
            return False
        self._shm_held.add(object_id)
        return True

    def adopt_shm(self, object_id: bytes) -> bool:
        """Pin an object a WORKER sealed directly into shm (its bytes never
        crossed an RPC) and publish its location."""
        if self._shm is None:
            return False
        view = self._shm.get(object_id)  # takes the pin ref
        if view is None:
            return False
        with self._lock:
            self._shm_held.add(object_id)
            self._arrived.notify_all()
        self._announce(object_id)
        return True

    def put(self, object_id: bytes, data: bytes) -> None:
        with self._lock:
            if object_id in self._shm_held:
                pass  # already resident in shm
            elif self._shm_put_pinned(object_id, data):
                pass
            else:
                old = self._objects.pop(object_id, None)
                if old is not None:
                    self._bytes -= len(old)
                self._objects[object_id] = data
                self._bytes += len(data)
                self._evict_over_capacity_locked()
            self._arrived.notify_all()  # unblock fetch() waiters instantly
        self._announce(object_id)

    def _announce(self, object_id: bytes) -> None:
        """Publish the location; a dark GCS only costs directory
        freshness — the bytes are stored and locally readable either way
        (degraded-mode contract: per-request paths never fail on the
        control plane). Deferred announcements flush from the heartbeat
        loop / the re-registration inventory."""
        try:
            self._gcs.call(
                "add_object_location",
                {"object_id": object_id, "node_id": self._node_id},
            )
        except (RpcError, RemoteError):
            with self._lock:
                self._unannounced.add(object_id)

    def flush_unannounced(self) -> None:
        """Re-announce puts that landed while the GCS was dark (called
        after a successful heartbeat)."""
        with self._lock:
            todo = list(self._unannounced)
        for oid in todo:
            try:
                self._gcs.call(
                    "add_object_location",
                    {"object_id": oid, "node_id": self._node_id},
                )
            except (RpcError, RemoteError):
                return  # still dark; retry on a later beat
            with self._lock:
                self._unannounced.discard(oid)

    def get_local(self, object_id: bytes) -> Optional[bytes]:
        if self._shm is not None and object_id in self._shm_held:
            data = self._shm.get_bytes(object_id)
            if data is not None:
                return data
        with self._lock:
            data = self._objects.get(object_id)
            if data is not None:
                self._objects.move_to_end(object_id)  # MRU
                return data
        return self._get_spilled(object_id)

    def in_shm(self, object_id: bytes) -> bool:
        """Is this object readable straight from the shm mapping?"""
        return self._shm is not None and object_id in self._shm_held

    def local_size(self, object_id: bytes) -> Optional[int]:
        """Size without materializing (chunk-serving metadata)."""
        if self._shm is not None and object_id in self._shm_held:
            n = self._shm.size_of(object_id)
            if n is not None:
                return n
        with self._lock:
            data = self._objects.get(object_id)
        if data is not None:
            return len(data)
        data = self._get_spilled(object_id)
        return None if data is None else len(data)

    def local_slice(self, object_id: bytes, offset: int,
                    length: int) -> Optional[bytes]:
        """One chunk of a local object — for shm objects this copies ONLY
        the slice (a full get_bytes per chunk would make cross-node pulls
        quadratic in object size)."""
        if self._shm is not None and object_id in self._shm_held:
            data = self._shm.get_slice(object_id, offset, length)
            if data is not None:
                return data
        data = self.get_local(object_id)
        return None if data is None else data[offset:offset + length]

    def _get_spilled(self, object_id: bytes) -> Optional[bytes]:
        with self._lock:
            if object_id in self._spilled:
                try:
                    with open(self._spill_path(object_id), "rb") as f:
                        data = f.read()
                except OSError:
                    self._spilled.discard(object_id)
                    return None
                # promote back into the memory tier
                self._objects[object_id] = data
                self._bytes += len(data)
                self._spilled.discard(object_id)
                try:
                    os.unlink(self._spill_path(object_id))
                except OSError:
                    pass
                self._evict_over_capacity_locked()
                return data
        return None

    def free(self, object_id: bytes) -> None:
        with self._lock:
            if object_id in self._shm_held:
                self._shm_held.discard(object_id)
                try:
                    self._shm.release(object_id)  # drop the pin
                    self._shm.delete(object_id)
                except OSError:
                    pass
            data = self._objects.pop(object_id, None)
            if data is not None:
                self._bytes -= len(data)
            if object_id in self._spilled:
                self._spilled.discard(object_id)
                try:
                    os.unlink(self._spill_path(object_id))
                except OSError:
                    pass
            self._unannounced.discard(object_id)
        try:
            self._gcs.call(
                "remove_object_location",
                {"object_id": object_id, "node_id": self._node_id},
            )
        except RpcError:
            pass

    def fetch(self, object_id: bytes, timeout: float = 30.0) -> Optional[bytes]:
        """Local hit or remote pull; single-object form of fetch_many."""
        return self.fetch_many([object_id], timeout)[0]

    SHM_MARKER = {"__shm__": True}

    def fetch_many(self, ids: list, timeout: float = 30.0,
                   shm_markers: bool = False) -> list:
        """Batched local-or-remote fetch, the ONE pull implementation.

        Local arrivals (the hot path: a worker's put_return racing the
        caller's get) wake waiters via condition variable — no 50 ms poll
        tax on fresh task results. Remote lookups are ONE batched
        locate_many per rate-limited round, not a per-object GCS call per
        wakeup (GCS thundering herd).

        shm_markers: the caller has the store mapped (a local driver) —
        shm-resident objects come back as SHM_MARKER without EVER being
        materialized into daemon-side bytes (the copy is the point of
        the fast path, not just the socket)."""
        deadline = time.monotonic() + timeout
        out: dict[bytes, Optional[bytes]] = {oid: None for oid in ids}
        missing = [oid for oid in dict.fromkeys(ids)]  # dedup, keep order
        next_remote = 0.0  # first round probes immediately
        while missing:
            still = []
            for oid in missing:
                if shm_markers and self.in_shm(oid):
                    out[oid] = self.SHM_MARKER
                    continue
                data = self.get_local(oid)
                if data is None:
                    still.append(oid)
                else:
                    out[oid] = data
            missing = still
            if not missing or time.monotonic() >= deadline:
                break
            if time.monotonic() >= next_remote:
                next_remote = time.monotonic() + 0.25
                try:
                    locs = self._gcs.call(
                        "locate_many", {"object_ids": missing}, timeout=10
                    )
                except (RpcError, RemoteError):
                    locs = {}
                for oid in list(missing):
                    for addr in locs.get(oid, ()):
                        if tuple(addr) == self._pool_self_addr:
                            continue
                        try:
                            data = self._pull_from(tuple(addr), oid)
                        except (RpcError, RemoteError):
                            continue
                        if data is not None:
                            self.put(oid, data)
                            out[oid] = data
                            missing.remove(oid)
                            break
            if not missing or time.monotonic() >= deadline:
                break
            with self._arrived:
                self._arrived.wait(timeout=0.05)
        return [out[oid] for oid in ids]

    _pool_self_addr: tuple = ("", 0)  # set by daemon after bind

    def _pull_from(self, addr: tuple, object_id: bytes) -> Optional[bytes]:
        c = self._pool.get(addr)
        meta = c.call("object_meta", {"object_id": object_id})
        if meta is None:
            return None
        size = meta["size"]
        parts = []
        off = 0
        while off < size:
            chunk = c.call(
                "object_chunk",
                {"object_id": object_id, "offset": off, "length": CHUNK},
            )
            if chunk is None:
                return None
            parts.append(chunk)
            off += len(chunk)
        return b"".join(parts)

    def inventory(self) -> list:
        """Every object id resident on this node (memory + spilled +
        shm tiers) — the re-registration report that rebuilds a restarted
        GCS's object directory."""
        with self._lock:
            return list(
                dict.fromkeys(
                    list(self._objects) + list(self._spilled)
                    + list(self._shm_held)
                )
            )

    def close(self) -> None:
        """Release pins and close (owner: unlink) the shm store."""
        if self._shm is None:
            return
        for oid in list(self._shm_held):
            try:
                self._shm.release(oid)
            except OSError:
                pass
        self._shm_held.clear()
        try:
            self._shm.close()
        except Exception:
            pass

    def stats(self) -> dict:
        with self._lock:
            out = {
                "num_objects": len(self._objects) + len(self._spilled)
                + len(self._shm_held),
                "bytes": self._bytes,
                "spilled": len(self._spilled),
                "capacity": self._capacity,
                "shm_objects": len(self._shm_held),
            }
            if self._shm is not None:
                try:
                    out["shm"] = self._shm.stats()
                except OSError:
                    pass
            return out


class WorkerHandle:
    def __init__(self, proc: subprocess.Popen, worker_id: str):
        self.proc = proc
        self.worker_id = worker_id
        self.addr: Optional[tuple] = None
        self.ready = threading.Event()
        self.env_key = ""  # runtime-env hash this worker is dedicated to
        self.idle_since = time.monotonic()

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        try:
            self.proc.kill()
        except Exception:
            pass


def _sweep_stale_stores(shm_dir: str) -> None:
    """Unlink object-store files whose owning daemon is gone: a SIGKILLed
    daemon (chaos tests, OOM kills) can't clean its own tmpfs file, and
    the leaks compound at hundreds of MB per killed node."""
    try:
        names = os.listdir(shm_dir)
    except OSError:
        return
    for name in names:
        if not name.startswith("ray_tpu-store-"):
            continue
        pid_s = name.rsplit("-", 1)[-1]
        if not pid_s.isdigit():
            continue
        try:
            os.kill(int(pid_s), 0)  # signal 0 = liveness probe
        except ProcessLookupError:
            try:
                os.unlink(os.path.join(shm_dir, name))
            except OSError:
                pass
        except PermissionError:
            pass  # someone else's live process


class NodeDaemon:
    """The per-node control process (raylet-equivalent)."""

    def __init__(
        self,
        gcs_addr: tuple,
        resources: dict,
        node_id: Optional[str] = None,
        host: str = "127.0.0.1",
        labels: Optional[dict] = None,
        worker_env: Optional[dict] = None,
        heartbeat_interval_s: float = 0.5,
        object_capacity_bytes: int = 512 << 20,
        worker_rss_limit_mb: int = 0,       # 0 = no per-worker cap
        memory_usage_threshold: float = 0.95,  # node pressure kill point
        memory_monitor_interval_s: float = 1.0,  # 0 = monitor disabled
        telemetry_interval_s: float = 2.0,  # 0 = no heartbeat piggyback
    ):
        self.node_id = node_id or f"node-{uuid.uuid4().hex[:8]}"
        self.gcs_addr = gcs_addr
        self.total = dict(resources)
        self.available = dict(resources)
        self.labels = labels or {}
        self.worker_env = worker_env or {}
        self._hb_interval = heartbeat_interval_s
        self._rss_limit_mb = int(worker_rss_limit_mb)
        self._mem_threshold = float(memory_usage_threshold)
        self._mem_interval = float(memory_monitor_interval_s)
        self._telemetry_interval = float(telemetry_interval_s)
        self._last_telemetry = 0.0
        self._oom_kills = 0
        # RLock: PG-bundle reserve is check-then-act over _bundles AND the
        # node availability — the whole sequence must be atomic across
        # handler threads (reference: PlacementGroupResourceManager commits
        # bundle resources atomically)
        self._res_lock = threading.RLock()
        self._leases: dict[str, dict] = {}  # lease_id -> {resources, worker}
        self._bundles: dict[tuple, dict] = {}  # (pg_id, idx) -> reserved resources
        # idle pool keyed by runtime-env hash: a worker only ever runs
        # tasks of ONE runtime env (reference: worker_pool.h dedicated
        # workers per runtime env)
        self._idle_workers: dict[str, list[WorkerHandle]] = {}
        self._all_workers: dict[str, WorkerHandle] = {}
        self._env_cache = os.path.join(
            os.environ.get("TMPDIR", "/tmp"),
            f"ray_tpu-envs-{node_id or 'node'}-{os.getpid()}",
        )
        self._wlock = threading.Lock()
        self._grant_queue: "queue_mod.Queue" = queue_mod.Queue()
        self._capacity_signal = threading.Event()  # wakes the granter
        self._num_queued = 0  # granter's current waiter count (approximate)
        self._pending_specs: list[dict] = []  # queued lease resource specs
        from collections import deque as _deque

        self._spans: "_deque[dict]" = _deque(maxlen=20000)  # worker exec spans
        self.rpc = RpcServer(self, host=host)
        self.pool = ClientPool()
        # reconnecting: the GCS may restart (FT snapshot) and come back at
        # the same address; the daemon must ride through the outage
        self.gcs = ReconnectingRpcClient(*gcs_addr).connect(retries=20)
        from ray_tpu.utils.shm import shm_dir as _shm_dir

        shm_dir = _shm_dir()
        _sweep_stale_stores(shm_dir)
        self.objects = ObjectService(
            self.node_id, self.gcs, self.pool,
            capacity_bytes=object_capacity_bytes,
            shm_path=os.path.join(
                shm_dir, f"ray_tpu-store-{self.node_id}-{os.getpid()}"
            ),
        )
        self._stop = threading.Event()
        # graceful drain (SIGTERM / maintenance event): stop admitting
        # leases, let in-flight work finish, deregister from the GCS
        self._draining = False
        self.addr: Optional[tuple] = None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> tuple:
        self.addr = self.rpc.start()
        self.objects._pool_self_addr = self.addr
        self.gcs.call(
            "register_node",
            {
                "node_id": self.node_id,
                "addr": self.addr,
                "resources": self.total,
                "labels": self.labels,
            },
        )
        t = threading.Thread(target=self._heartbeat_loop, name="node-hb", daemon=True)
        t.start()
        threading.Thread(
            target=self._granter_loop, name="node-granter", daemon=True
        ).start()
        if self._mem_interval > 0:
            threading.Thread(
                target=self._memory_monitor_loop, name="node-memmon",
                daemon=True,
            ).start()
        return self.addr

    # -- memory monitor -------------------------------------------------------
    # Reference analog: src/ray/raylet/worker_killing_policy.cc — under
    # node memory pressure the raylet kills workers (retriable tasks
    # first, newest first) instead of letting the kernel OOM-killer take
    # out the daemon or an arbitrary process. Two triggers here:
    #   * per-worker RSS cap (worker_rss_limit_mb): a deterministic cap
    #     against one runaway task;
    #   * node usage threshold (memory_usage_threshold over
    #     /proc/meminfo): kill the NEWEST leased worker — its pusher gets
    #     a connection error and the task re-leases under max_retries,
    #     exactly the retriable-FIFO policy's assumption.

    @staticmethod
    def _worker_rss_mb(pid: int) -> float:
        try:
            with open(f"/proc/{pid}/statm") as f:
                pages = int(f.read().split()[1])
            return pages * (os.sysconf("SC_PAGE_SIZE") / (1 << 20))
        except (OSError, ValueError, IndexError):
            return 0.0

    @staticmethod
    def _node_memory_usage() -> float:
        try:
            info = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    k, v = line.split(":", 1)
                    info[k] = int(v.strip().split()[0])
            total = info.get("MemTotal", 0)
            avail = info.get("MemAvailable", total)
            return 1.0 - avail / total if total else 0.0
        except (OSError, ValueError):
            return 0.0

    def _memory_monitor_loop(self) -> None:
        while not self._stop.wait(self._mem_interval):
            try:
                self._memory_check()
            except Exception:  # noqa: BLE001 — monitor must never die
                logger.exception("memory monitor tick failed")

    def _memory_check(self) -> None:
        # reap corpses first: a worker the monitor killed last tick must
        # leave _all_workers/_idle_workers, or the newest-first selection
        # would livelock re-killing the same dead handle every tick while
        # live workers hold the actual memory
        with self._wlock:
            dead = [w for w in self._all_workers.values() if not w.alive()]
            for w in dead:
                self._all_workers.pop(w.worker_id, None)
            for key, pool in list(self._idle_workers.items()):
                keep = [w for w in pool if w.alive()]
                if keep:
                    self._idle_workers[key] = keep
                else:
                    self._idle_workers.pop(key, None)
            workers = list(self._all_workers.values())
        victims: list[tuple] = []
        if self._rss_limit_mb > 0:
            for w in workers:
                if not w.alive():
                    continue
                rss = self._worker_rss_mb(w.proc.pid)
                if rss > self._rss_limit_mb:
                    victims.append((w, f"rss {rss:.0f}MB > limit "
                                       f"{self._rss_limit_mb}MB"))
        if not victims and self._mem_threshold < 1.0:
            usage = self._node_memory_usage()
            if usage > self._mem_threshold:
                # RETRIABLE leases first, newest first (reference
                # worker_killing_policy: a max_retries=0 task dies for
                # good if its worker is killed — only shed it when no
                # retriable victim exists); fall back to the newest idle
                # worker to shed pool memory
                with self._res_lock:
                    leased = sorted(
                        (ls for ls in self._leases.values()
                         if ls.get("worker") is not None
                         and ls["worker"].alive()),
                        key=lambda ls: (
                            not ls.get("retriable", True), -ls.get("t", 0.0)
                        ),
                    )
                live = [w for w in workers if w.alive()]
                if leased:
                    pick = leased[0]
                    victims.append((
                        pick["worker"],
                        f"node memory {usage:.0%} > "
                        f"{self._mem_threshold:.0%} "
                        f"({'retriable' if pick.get('retriable', True) else 'NON-retriable (no retriable victim)'} lease)",
                    ))
                elif live:
                    victims.append((
                        max(live, key=lambda w: w.idle_since),
                        f"node memory {usage:.0%} (idle worker)",
                    ))
        for w, why in victims:
            logger.warning(
                "memory monitor killing worker %s (pid %s): %s",
                w.worker_id, w.proc.pid, why,
            )
            self._oom_kills += 1
            w.kill()

    def drain(self, timeout_s: float = 30.0) -> dict:
        """Graceful drain (SIGTERM / maintenance event): stop admitting
        leases, wait for in-flight leases to finish (bounded), deregister
        from the GCS, then stop. In-flight work either completes here or
        — if the timeout expires — dies with the node and re-homes via
        the caller's normal retry path (max_retries / actor restart)."""
        self._draining = True
        logger.warning("node %s draining (timeout %.1fs)", self.node_id, timeout_s)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._res_lock:
                inflight = len(self._leases)
            if inflight == 0 and self._grant_queue.qsize() == 0 \
                    and self._num_queued == 0:
                break
            time.sleep(0.1)
        with self._res_lock:
            leaked = len(self._leases)
        if leaked:
            logger.warning(
                "node %s drain timeout with %d leases in flight; "
                "their tasks will re-home via retry", self.node_id, leaked,
            )
        self.stop()  # stop() deregisters via drain_node before teardown
        return {"ok": True, "leases_killed": leaked}

    def rpc_drain(self, payload, peer):
        """Remote maintenance trigger (the autoscaler's scale-down /
        preemption-notice path); drains on a background thread so the
        RPC answers immediately."""
        timeout_s = float((payload or {}).get("timeout_s", 30.0))
        threading.Thread(
            target=self.drain, args=(timeout_s,), name="node-drain", daemon=True
        ).start()
        return {"ok": True, "draining": True}

    def rpc_chaos_kill_worker(self, payload, peer):
        """Fault-injection surface (chaos.runner): SIGKILL the newest
        leased worker — the deterministic stand-in for a worker OOM/crash
        mid-task."""
        with self._res_lock:
            leased = sorted(
                (ls for ls in self._leases.values()
                 if ls.get("worker") is not None and ls["worker"].alive()),
                key=lambda ls: -ls.get("t", 0.0),
            )
        if not leased:
            return {"ok": False, "error": "no leased worker to kill"}
        w = leased[0]["worker"]
        logger.warning("chaos: killing worker %s (pid %s)", w.worker_id, w.proc.pid)
        w.kill()
        return {"ok": True, "worker_id": w.worker_id}

    def stop(self) -> None:
        self._stop.set()
        with self._wlock:
            for w in self._all_workers.values():
                w.kill()
            self._all_workers.clear()
            self._idle_workers.clear()
        try:
            self.gcs.call("drain_node", {"node_id": self.node_id}, timeout=2)
        except (RpcError, RemoteError):
            pass
        self.rpc.stop()
        self.gcs.close()
        self.pool.close_all()
        self.objects.close()  # releases pins; owner unlinks the tmpfs file

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self._hb_interval):
            try:
                self._reap_idle_workers()
            except Exception:
                pass
            if _chaos.ACTIVE is not None and any(
                f.kind == _chaos.STALL_HEARTBEAT
                for f in _chaos.fire("node.heartbeat",
                                     kinds=(_chaos.STALL_HEARTBEAT,),
                                     node_id=self.node_id)
            ):
                # partition simulation: the node is alive and working but
                # its heartbeats never reach the GCS — the exact shape of
                # a network partition / GC pause the _mark_dead sweeper
                # turns into a (possibly premature) death verdict
                continue
            try:
                with self._res_lock:
                    avail = dict(self.available)
                hb = {"node_id": self.node_id, "available": avail,
                      "pending": self._pending_specs,
                      "draining": self._draining}
                if (
                    self._telemetry_interval > 0
                    and time.monotonic() - self._last_telemetry
                    >= self._telemetry_interval
                ):
                    # piggybacked metrics snapshot (obs/telemetry.py):
                    # absolute totals, so a beat the chaos STALL drops
                    # only costs freshness — staleness is the GCS's
                    # reported metric for exactly that
                    try:
                        hb["telemetry"] = self._telemetry_snapshot()
                        self._last_telemetry = time.monotonic()
                    except Exception:  # noqa: BLE001 — never break heartbeats
                        logger.exception("telemetry snapshot failed")
                r = self.gcs.call("heartbeat", hb, timeout=5)
                if not r.get("ok") and r.get("reregister"):
                    # a restarted/partition-recovered GCS asked for ground
                    # truth: re-register with the FULL reconcile report —
                    # object inventory, held leases, reserved PG bundles,
                    # and the live actors our workers host — so the GCS
                    # converges its (possibly stale) snapshot to reality
                    self.gcs.call(
                        "register_node",
                        {
                            "node_id": self.node_id,
                            "addr": self.addr,
                            "resources": self.total,
                            "labels": self.labels,
                            **self._reconcile_report(),
                        },
                    )
                else:
                    self.objects.flush_unannounced()
            except (RpcError, RemoteError):
                pass  # GCS down: keep trying (it may restart)

    def _reconcile_report(self) -> dict:
        """Ground truth for a reconciling GCS: everything live on this
        node right now. Worker actor inventories are collected over
        bounded RPCs; a worker that died mid-collect simply contributes
        nothing (its actors are genuinely gone)."""
        with self._res_lock:
            leases = [
                {
                    "lease_id": lid,
                    "resources": dict(ls["resources"]),
                    "worker_id": getattr(ls.get("worker"), "worker_id", None),
                }
                for lid, ls in self._leases.items()
            ]
            bundles = [
                {"pg_id": pg_id, "bundle_index": idx,
                 "resources": dict(res)}
                for (pg_id, idx), res in self._bundles.items()
            ]
            worker_by_lease = {
                lid: ls.get("worker") for lid, ls in self._leases.items()
            }
        actors: list[dict] = []
        for lid, w in worker_by_lease.items():
            if w is None or not w.alive() or w.addr is None:
                continue
            try:
                inv = self.pool.get(tuple(w.addr)).call(
                    "actor_inventory", {}, timeout=5
                )
            except (RpcError, RemoteError):
                continue
            for rec in inv or ():
                rec = dict(rec)
                rec.setdefault("lease_id", lid)
                rec.setdefault("worker_addr", tuple(w.addr))
                actors.append(rec)
        return {
            "objects": self.objects.inventory(),
            "leases": leases,
            "bundles": bundles,
            "actors": actors,
        }

    # -- resources ------------------------------------------------------------

    def _try_acquire(self, res: dict, pool: Optional[dict] = None) -> bool:
        with self._res_lock:
            target = pool if pool is not None else self.available
            if all(target.get(k, 0.0) >= v - 1e-9 for k, v in res.items()):
                for k, v in res.items():
                    target[k] = target.get(k, 0.0) - v
                return True
            return False

    def _release(self, res: dict, pool: Optional[dict] = None) -> None:
        with self._res_lock:
            target = pool if pool is not None else self.available
            for k, v in res.items():
                target[k] = target.get(k, 0.0) + v

    # -- worker pool ----------------------------------------------------------

    def _spawn_worker(self, runtime_env: Optional[dict] = None) -> WorkerHandle:
        worker_id = f"w-{uuid.uuid4().hex[:8]}"
        env = dict(os.environ)
        env.update(self.worker_env)
        # the worker must import ray_tpu REGARDLESS of its cwd: a
        # runtime_env working_dir changes cwd to the materialized
        # package, dropping any implicit cwd-based import
        from ray_tpu.utils.env import inject_framework_pythonpath

        inject_framework_pythonpath(env)
        env["RAY_TPU_WORKER_ID"] = worker_id
        env["RAY_TPU_NODE_ID"] = self.node_id
        # the host workers should advertise for cross-host rendezvous
        # (jax.distributed coordinator election reads this)
        env["RAY_TPU_NODE_IP"] = self.addr[0]
        cwd = os.getcwd()
        env_key = ""
        if runtime_env:
            from ray_tpu.cluster.runtime_env import env_hash, materialize
            from ray_tpu.cluster.serialization import loads_value

            env_key = env_hash(runtime_env)

            def fetch_bytes(oid):
                data = self.objects.fetch(oid, timeout=60.0)
                return None if data is None else loads_value(data, lambda _: None)

            extra, workdir = materialize(
                runtime_env, fetch_bytes, self._env_cache, base_env=env
            )
            env.update(extra)
            if workdir:
                cwd = workdir
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "ray_tpu.cluster.worker_main",
                "--daemon", f"{self.addr[0]}:{self.addr[1]}",
                "--worker-id", worker_id,
                "--gcs", format_gcs_addr(self.gcs_addr),
            ],
            env=env,
            cwd=cwd,
        )
        h = WorkerHandle(proc, worker_id)
        h.env_key = env_key
        with self._wlock:
            self._all_workers[worker_id] = h
        return h

    def _lease_worker(self, block: bool = True,
                      runtime_env: Optional[dict] = None) -> Optional[WorkerHandle]:
        from ray_tpu.cluster.runtime_env import env_hash

        key = env_hash(runtime_env)
        with self._wlock:
            pool = self._idle_workers.get(key, [])
            while pool:
                w = pool.pop()
                if w.alive():
                    # a live worker trumps any stale spawn error
                    getattr(self, "_spawn_errors", {}).pop(key, None)
                    return w
            err = getattr(self, "_spawn_errors", {}).pop(key, None)
            if err is not None:
                # a background spawn for this env failed (bad runtime_env,
                # missing package): surface it instead of retrying forever
                raise RpcError(f"worker spawn failed: {err}")
        if not block:
            # the single granter thread must never sit in a multi-second
            # worker spawn (it would stall every other queued lease):
            # kick an async spawn and let the capacity signal re-trigger
            self._ensure_spawning(runtime_env, key)
            return None
        w = self._spawn_worker(runtime_env)
        if not w.ready.wait(timeout=60):
            w.kill()
            raise RpcError("worker failed to start in 60s")
        return w

    def _ensure_spawning(self, runtime_env: Optional[dict], key: str) -> None:
        """At most one background worker spawn in flight per runtime env."""
        with self._wlock:
            spawning = getattr(self, "_spawning", None)
            if spawning is None:
                spawning = self._spawning = set()
            if not hasattr(self, "_spawn_errors"):
                self._spawn_errors: dict[str, str] = {}
            if key in spawning:
                return
            spawning.add(key)

        def run():
            try:
                w = self._spawn_worker(runtime_env)
                if w.ready.wait(timeout=60) and w.alive():
                    w.idle_since = time.monotonic()
                    with self._wlock:
                        self._idle_workers.setdefault(key, []).append(w)
                else:
                    w.kill()
                    with self._wlock:
                        self._spawn_errors[key] = "worker failed to start in 60s"
            except Exception as e:  # noqa: BLE001 - deliver to the waiter
                with self._wlock:
                    self._spawn_errors[key] = repr(e)
            finally:
                with self._wlock:
                    self._spawning.discard(key)
                self._notify_capacity()

        threading.Thread(target=run, name="worker-spawn", daemon=True).start()

    def _reap_idle_workers(self, ttl_s: float = 60.0) -> None:
        """Kill runtime-env-dedicated workers idle past their TTL; the
        default ("") pool is exempt (reference: worker_pool idle-worker
        killing for dedicated workers)."""
        now = time.monotonic()
        doomed: list[WorkerHandle] = []
        with self._wlock:
            for key, pool in list(self._idle_workers.items()):
                if key == "":
                    continue
                keep = []
                for w in pool:
                    if now - getattr(w, "idle_since", now) > ttl_s:
                        doomed.append(w)
                    else:
                        keep.append(w)
                if keep:
                    self._idle_workers[key] = keep
                else:
                    self._idle_workers.pop(key, None)
            for w in doomed:
                self._all_workers.pop(w.worker_id, None)
        for w in doomed:
            w.kill()

    def rpc_register_worker(self, payload, peer):
        with self._wlock:
            w = self._all_workers.get(payload["worker_id"])
        if w is None:
            return {"ok": False}
        w.addr = tuple(payload["addr"])
        w.ready.set()
        return {
            "ok": True,
            "node_id": self.node_id,
            "gcs_addr": self.gcs_addr,
            "daemon_addr": self.addr,
            "shm_path": self.objects.shm_path,
        }

    # -- lease protocol -------------------------------------------------------

    def _try_grant(self, payload, allow_spillback: bool = True,
                   block_spawn: bool = True) -> Optional[dict]:
        """One grant attempt. Returns a response dict, or None when the
        request should QUEUE here (no capacity now, no better node).

        allow_spillback=False on queue retries: recomputing spillback
        candidates means a GCS list_nodes per waiter per wakeup — a
        thundering herd that serializes the whole cluster on the GCS."""
        res = payload.get("resources", {})
        pg_key = None
        if self._draining and (
            payload.get("pg_id") is not None or payload.get("pinned")
        ):
            # placement here is mandatory but the node is leaving: fail
            # fast so the caller re-resolves instead of queueing into a
            # node that will never grant again
            return {"error": f"node {self.node_id} is draining"}
        if payload.get("pg_id") is not None:
            pg_key = (payload["pg_id"], payload.get("bundle_index", 0))
            with self._res_lock:
                bundle_pool = self._bundles.get(pg_key)
                if bundle_pool is None:
                    return {"error": f"no bundle reserved here for {pg_key}"}
                acquired = self._try_acquire(res, bundle_pool)
        else:
            # a draining node stops admitting new leases entirely
            acquired = (not self._draining) and self._try_acquire(res)
        if acquired:
            try:
                w = self._lease_worker(
                    block=block_spawn, runtime_env=payload.get("runtime_env")
                )
            except Exception as e:  # noqa: BLE001 - incl. runtime_env failures
                with self._res_lock:
                    self._release(
                        res, self._bundles.get(pg_key) if pg_key else None
                    )
                return {"error": str(e)}
            if w is None:  # spawn in flight; re-queue until it registers
                with self._res_lock:
                    self._release(
                        res, self._bundles.get(pg_key) if pg_key else None
                    )
                return None
            lease_id = uuid.uuid4().hex
            self._leases[lease_id] = {
                "resources": res, "worker": w, "pg_key": pg_key,
                "t": time.monotonic(),  # newest-first OOM kill policy
                "retriable": bool(payload.get("retriable", True)),
            }
            return {
                "grant": {
                    "lease_id": lease_id,
                    "worker_addr": w.addr,
                    "worker_id": w.worker_id,
                    "node_id": self.node_id,
                    # the address release_lease must go to — without it a
                    # remote actor's lease could only ever be released at
                    # the driver's local daemon (leaking worker+resources)
                    "node_addr": self.addr,
                }
            }
        # no local capacity: pg/pinned requests always queue here
        if pg_key is not None or payload.get("pinned") or not allow_spillback:
            return None
        # spillback: consult the GCS view for a node that fits
        exclude = set(payload.get("exclude", ())) | {self.node_id}
        try:
            nodes = self.gcs.call("list_nodes", None, timeout=5)
        except (RpcError, RemoteError):
            nodes = []
        candidates = [
            n for n in nodes
            if n["alive"] and not n.get("draining")
            and n["node_id"] not in exclude
            and all(n["available"].get(k, 0.0) >= v for k, v in res.items())
        ]
        if candidates:
            # hybrid policy's remote leg: random among the top-k by
            # availability, so concurrent submitters with the same (stale)
            # view don't all herd onto one node
            # (reference: hybrid_scheduling_policy.h:29-49)
            import random

            key = next(iter(res), None)
            random.shuffle(candidates)
            candidates.sort(
                key=lambda n: -n["available"].get(key, 0.0) if key else 0.0
            )
            top_k = candidates[: max(1, min(3, len(candidates)))]
            pick = random.choice(top_k)
            return {"spillback": pick["addr"],
                    "spillback_node": pick["node_id"],
                    "node_id": self.node_id}
        if self._draining:
            # never queue on a draining node: tell the client to retry
            # (somewhere else, or here again once replacement capacity
            # registers) instead of parking until the drain kills us
            return {"retry_after": 0.25, "node_id": self.node_id,
                    "draining": True}
        return None  # saturated cluster: queue here

    async def rpc_request_worker_lease(self, payload, peer):
        """Grant a worker, spill back, or QUEUE the request server-side
        until capacity frees (reference: ClusterTaskManager queues leases,
        src/ray/raylet/scheduling/cluster_task_manager.h — the round-2
        50 ms client busy-poll is gone). Queued requests are granted FIFO
        by ONE granter thread: a broadcast wakeup would retry every
        waiter on every release (thundering herd).
        """
        loop = asyncio.get_running_loop()
        # fast path only when nobody is queued — otherwise new arrivals
        # would steal freed capacity from FIFO waiters (starvation)
        if self._grant_queue.qsize() == 0 and self._num_queued == 0:
            r = await loop.run_in_executor(None, self._try_grant, payload, True)
            if r is not None:
                return r
        fut = loop.create_future()
        deadline = time.monotonic() + float(payload.get("queue_timeout", 30.0))
        self._grant_queue.put((payload, loop, fut, deadline))
        return await fut

    async def rpc_request_worker_lease_batch(self, payload, peer):
        """Batched lease grants (r20 control-plane batching): N specs in
        one RPC, granted in one executor hop instead of N dispatch
        round-trips. Fast-path only — when waiters are queued, batch
        arrivals must not steal freed capacity from FIFO waiters, so
        every spec is answered ``retry_after`` (individually or via the
        queueing ``request_worker_lease`` path). Results keep order."""
        requests = list(payload.get("requests", ()))

        def _grant_all() -> list:
            out = []
            for spec in requests:
                if self._grant_queue.qsize() > 0 or self._num_queued > 0:
                    out.append(
                        {"retry_after": 0.05, "node_id": self.node_id}
                    )
                    continue
                try:
                    r = self._try_grant(spec, True)
                except Exception as e:  # noqa: BLE001 — per-spec isolation
                    r = {"error": f"{type(e).__name__}: {e}",
                         "node_id": self.node_id}
                out.append(
                    r if r is not None
                    else {"retry_after": 0.05, "node_id": self.node_id}
                )
            return out

        loop = asyncio.get_running_loop()
        grants = await loop.run_in_executor(None, _grant_all)
        return {"ok": True, "grants": grants}

    def _granter_loop(self) -> None:
        """Server-side lease queue (the ClusterTaskManager role).

        Scans ALL waiters each round in arrival order: a blocked head
        (e.g. a fixed-bundle request on a busy bundle) must not stall
        requests for other bundles/resources behind it. Any exception in
        a grant attempt answers THAT waiter with an error — the granter
        thread itself must never die (every queued future would hang)."""
        waiters: list = []  # [payload, loop, fut, deadline, next_spill]
        while not self._stop.is_set():
            try:  # drain new arrivals
                while True:
                    item = self._grant_queue.get_nowait()
                    waiters.append(list(item) + [time.monotonic() + 0.5])
            except queue_mod.Empty:
                pass
            if not waiters:
                try:
                    item = self._grant_queue.get(timeout=0.5)
                    waiters.append(list(item) + [time.monotonic() + 0.5])
                except queue_mod.Empty:
                    continue
            progressed = False
            still: list = []
            for waiter in waiters:
                payload, loop, fut, deadline, next_spill = waiter
                # while queued, periodically re-check the GCS for a node
                # with free capacity — the local queue must not starve a
                # task the rest of the cluster could run right now. The
                # request's exclude list is DROPPED for these probes: it
                # records nodes that were full when the client hopped
                # through them, and by now (>=0.5s later, a fresh heartbeat)
                # those views are stale — keeping it would permanently
                # blind the queue to a node that has since freed up
                spill = time.monotonic() >= next_spill and not payload.get("pinned")
                probe = payload
                if spill and payload.get("exclude"):
                    probe = {k: v for k, v in payload.items() if k != "exclude"}
                try:
                    r = self._try_grant(
                        probe, allow_spillback=spill, block_spawn=False
                    )
                except Exception as e:  # noqa: BLE001 - must not kill the granter
                    logger.exception("lease grant attempt failed")
                    r = {"error": f"lease grant failed: {e!r}"}
                if spill:
                    waiter[4] = time.monotonic() + 1.0
                if r is None and time.monotonic() >= deadline:
                    # let the client re-evaluate (capacity may exist under
                    # a different exclude set by now)
                    r = {"retry_after": 0.05, "node_id": self.node_id}
                if r is None:
                    still.append(waiter)
                    continue
                progressed = True

                def _finish(f=fut, rr=r):
                    if f.cancelled():
                        # requester vanished after we granted: reclaim the
                        # lease or it (worker + resources) leaks forever
                        self._reclaim_grant(rr)
                        return
                    f.set_result(rr)

                try:
                    loop.call_soon_threadsafe(_finish)
                except RuntimeError:
                    self._reclaim_grant(r)  # connection's loop is gone
            waiters = still
            self._num_queued = len(waiters)
            # autoscaler demand feed: specs of leases parked here, shipped
            # to the GCS with the next heartbeat (reference: resource
            # demand in raylet heartbeats driving the autoscaler)
            self._pending_specs = [
                dict(w[0].get("resources", {})) for w in waiters[:64]
            ]
            if waiters and not progressed:
                self._capacity_signal.wait(timeout=0.1)
                self._capacity_signal.clear()

    def _reclaim_grant(self, response: dict) -> None:
        """Release a lease whose grant could not be delivered."""
        grant = response.get("grant") if isinstance(response, dict) else None
        if grant:
            try:
                self.rpc_release_lease(
                    {"lease_id": grant["lease_id"], "kill": False}, None
                )
            except Exception:
                logger.exception("reclaiming undeliverable grant failed")

    def _notify_capacity(self) -> None:
        """Wake the granter (called from release paths, any thread)."""
        self._capacity_signal.set()

    def rpc_release_lease(self, payload, peer):
        lease = self._leases.pop(payload["lease_id"], None)
        if lease is None:
            return {"ok": False}
        # worker back to the idle pool BEFORE freeing resources: the
        # granter races on freed capacity, and losing this race makes it
        # spawn a brand-new worker process (seconds) instead of reusing
        # the one we are returning right now
        w: WorkerHandle = lease["worker"]
        if payload.get("kill") or not w.alive():
            w.kill()
            with self._wlock:
                self._all_workers.pop(w.worker_id, None)
        else:
            w.idle_since = time.monotonic()
            with self._wlock:
                self._idle_workers.setdefault(w.env_key, []).append(w)
        with self._res_lock:
            pool = self._bundles.get(lease["pg_key"]) if lease["pg_key"] else None
            self._release(lease["resources"], pool)
        self._notify_capacity()
        return {"ok": True}

    # -- placement group bundles ----------------------------------------------

    def rpc_reserve_pg_bundle(self, payload, peer):
        key = (payload["pg_id"], payload["bundle_index"])
        res = payload["resources"]
        with self._res_lock:  # atomic check-then-reserve across handlers
            if key in self._bundles:
                return {"ok": True}  # idempotent
            if not self._try_acquire(res):
                return {"ok": False, "error": "insufficient resources"}
            self._bundles[key] = dict(res)
        self._notify_capacity()  # pg-queued leases can now be granted
        return {"ok": True}

    def rpc_release_pg_bundle(self, payload, peer):
        key = (payload["pg_id"], payload["bundle_index"])
        with self._res_lock:
            pool = self._bundles.pop(key, None)
            if pool is None:
                return {"ok": False}
            # return whatever is still reserved plus whatever tasks gave back
            self._release(pool)
        self._notify_capacity()
        return {"ok": True}

    def rpc_release_pg_all(self, payload, peer):
        pg_id = payload["pg_id"]
        with self._res_lock:
            for key in [k for k in self._bundles if k[0] == pg_id]:
                self._release(self._bundles.pop(key))
        self._notify_capacity()
        return {"ok": True}

    # -- object service -------------------------------------------------------

    def rpc_put_object(self, payload, peer):
        self.objects.put(payload["object_id"], payload["data"])
        return {"ok": True}

    def rpc_object_sealed(self, payload, peer):
        """A colocated worker sealed this object straight into the shared-
        memory store — adopt (pin) it; the bytes never cross an RPC
        (reference: plasma seal notification, plasma/client.cc)."""
        return {"ok": self.objects.adopt_shm(payload["object_id"])}

    def rpc_object_meta(self, payload, peer):
        size = self.objects.local_size(payload["object_id"])
        return None if size is None else {"size": size}

    def rpc_object_chunk(self, payload, peer):
        return self.objects.local_slice(
            payload["object_id"], payload["offset"], payload["length"]
        )

    def rpc_fetch_object(self, payload, peer):
        """Blocking local-or-remote fetch (driver/worker `get` path)."""
        return self.objects.fetch(
            payload["object_id"], timeout=payload.get("timeout", 30.0)
        )

    def rpc_fetch_objects(self, payload, peer):
        """Batched fetch in ONE handler thread (a wide batch of blocking
        single fetches would pin one executor thread per ref and starve
        the daemon's put path — deadlock under load).

        shm_direct: the caller has the node's shm store mapped (a local
        driver) — SEALED shm objects come back as a {"__shm__"} marker
        it reads zero-RPC from the mapping; the daemon never even
        materializes the bytes (the large-task-return bandwidth
        ceiling, round-5 profile)."""
        return self.objects.fetch_many(
            payload["object_ids"], timeout=payload.get("timeout", 30.0),
            shm_markers=bool(payload.get("shm_direct")),
        )

    def rpc_has_object(self, payload, peer):
        return self.objects.get_local(payload["object_id"]) is not None

    def rpc_free_object(self, payload, peer):
        self.objects.free(payload["object_id"])
        return {"ok": True}

    # -- misc -----------------------------------------------------------------

    def rpc_ping(self, payload, peer):
        return {"node_id": self.node_id}

    def rpc_shm_info(self, payload, peer):
        """Local clients (drivers) attach the store read-side with this —
        the plasma-client role (same handshake workers get in register)."""
        return {"shm_path": self.objects.shm_path}

    def rpc_record_spans(self, payload, peer):
        """Batched execution spans from this node's workers (reference:
        worker ProfileEvents flowing to the task-event pipeline). Bounded
        buffer; rpc_timeline serves it to the dashboard/state API."""
        self._spans.extend(payload.get("spans", ()))
        return {"ok": True}

    def rpc_timeline(self, payload, peer):
        since = float(payload.get("since", 0.0)) if payload else 0.0
        return [s for s in list(self._spans)
                if float(s.get("end", 0.0)) >= since]

    def _telemetry_snapshot(self) -> dict:
        """Refresh this node's utilization gauges, then snapshot ONLY the
        series this daemon owns (name prefix + node tag): a test daemon
        colocated with other subsystems in one process must not re-ship
        their series under its own reporter id (double count)."""
        from ray_tpu.obs.telemetry import annotated_snapshot

        g = _node_gauges()
        tags = {"node": self.node_id}
        with self._wlock:
            num_workers = len(self._all_workers)
        with self._res_lock:
            num_leases = len(self._leases)
        g["workers"].set(num_workers, tags=tags)
        g["leases"].set(num_leases, tags=tags)
        g["queued_leases"].set(self._num_queued, tags=tags)
        g["object_bytes"].set(self.objects.stats()["bytes"], tags=tags)
        g["oom_kills"].set(self._oom_kills, tags=tags)
        node_id = self.node_id
        return annotated_snapshot(
            lambda name, t: name.startswith("ray_tpu_node_")
            and t.get("node") == node_id
        )

    def rpc_stats(self, payload, peer):
        # invariant: _all_workers is _wlock state — snapshot it under its
        # own lock BEFORE _res_lock (never nested: lock-order discipline)
        with self._wlock:
            num_workers = len(self._all_workers)
        with self._res_lock:
            return {
                "node_id": self.node_id,
                "total": dict(self.total),
                "available": dict(self.available),
                "num_leases": len(self._leases),
                "num_oom_kills": self._oom_kills,
                "num_workers": num_workers,
                "objects": self.objects.stats(),
            }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--gcs", required=True)
    p.add_argument("--node-id", default=None)
    p.add_argument("--resources", default="num_cpus=1")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--worker-env", default="", help="k=v,... for worker processes")
    p.add_argument("--object-capacity", type=int, default=512 << 20,
                   help="object store memory tier cap in bytes (LRU spills to disk)")
    p.add_argument("--worker-rss-limit-mb", type=int, default=0,
                   help="kill any worker whose RSS exceeds this (0 = off)")
    p.add_argument("--memory-usage-threshold", type=float, default=0.95,
                   help="node memory fraction that triggers worker kills "
                        "(>=1.0 disables the pressure trigger)")
    p.add_argument("--memory-monitor-interval", type=float, default=1.0,
                   help="memory monitor tick seconds (0 disables entirely)")
    p.add_argument("--telemetry-interval", type=float, default=2.0,
                   help="seconds between metrics snapshots piggybacked on "
                        "heartbeats (0 disables)")
    p.add_argument("--slice", default=None,
                   help="ICI slice id this host belongs to; advertises the "
                        "slice:<id> resource that fabric slice pools "
                        "(ray_tpu.fabric.pool) pin placement-group bundles "
                        "to, count = --slice-chips")
    p.add_argument("--slice-chips", type=float, default=4.0,
                   help="units of the slice:<id> resource to advertise "
                        "(chips of this slice hosted here)")
    args = p.parse_args()
    gcs_addr = parse_gcs_addr(args.gcs)  # "h:p" or HA pair "h1:p1,h2:p2"
    resources: dict[str, float] = {}
    for kv in args.resources.split(","):
        if kv:
            k, v = kv.split("=")
            resources[k] = float(v)
    if args.slice:
        # same name fabric.pool.slice_resource() generates — a host
        # belongs to exactly one ICI slice, and slice pools STRICT_PACK
        # their bundles against this resource
        resources.setdefault(f"slice:{args.slice}", args.slice_chips)
    worker_env: dict[str, str] = {}
    for kv in args.worker_env.split(","):
        if kv:
            k, v = kv.split("=", 1)
            worker_env[k] = v
    _chaos.install_from_env()  # adopt a driver-propagated fault schedule
    daemon = NodeDaemon(
        gcs_addr, resources, node_id=args.node_id, worker_env=worker_env,
        object_capacity_bytes=args.object_capacity,
        worker_rss_limit_mb=args.worker_rss_limit_mb,
        memory_usage_threshold=args.memory_usage_threshold,
        memory_monitor_interval_s=args.memory_monitor_interval,
        telemetry_interval_s=args.telemetry_interval,
    )
    addr = daemon.start()
    print(f"NODE_ADDRESS {addr[0]}:{addr[1]} {daemon.node_id}", flush=True)

    import signal

    def _on_sigterm(signum, frame):
        # graceful-drain contract: stop admission, finish in-flight work,
        # deregister from the GCS, exit — run off the signal frame so
        # blocking waits are legal
        def _run():
            daemon.drain(timeout_s=float(
                os.environ.get("RAY_TPU_DRAIN_TIMEOUT_S", "30")
            ))
            os._exit(0)

        threading.Thread(target=_run, name="sigterm-drain", daemon=True).start()

    signal.signal(signal.SIGTERM, _on_sigterm)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        daemon.stop()


if __name__ == "__main__":
    main()
