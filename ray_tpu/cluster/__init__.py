"""Distributed runtime: GCS service, node daemons, workers, driver client.

The multi-process control plane (reference: src/ray/gcs + src/ray/raylet
+ src/ray/core_worker split across processes). The single-process
runtime in ray_tpu.core stays the TPU-host fast path; this package is
the cross-process / cross-host tier.
"""

from ray_tpu.cluster.client import (
    ActorDiedError,
    ClusterActorHandle,
    ClusterClient,
    ClusterObjectRef,
    ClusterTaskError,
    GetTimeoutError,
)
from ray_tpu.cluster.cluster import LocalCluster
from ray_tpu.cluster.gcs_service import GcsServer, GcsService
from ray_tpu.cluster.node_daemon import NodeDaemon
from ray_tpu.cluster.rpc import ClientPool, RemoteError, RpcClient, RpcError, RpcServer

__all__ = [
    "ActorDiedError",
    "ClientPool",
    "ClusterActorHandle",
    "ClusterClient",
    "ClusterObjectRef",
    "ClusterTaskError",
    "GcsServer",
    "GcsService",
    "GetTimeoutError",
    "LocalCluster",
    "NodeDaemon",
    "RemoteError",
    "RpcClient",
    "RpcError",
    "RpcServer",
]
