"""GCS service: the cluster control-plane server process.

Reference analog: src/ray/gcs/gcs_server/ (GcsServer gcs_server.cc,
GcsNodeManager, GcsActorManager gcs_actor_manager.h:324,
GcsPlacementGroupManager gcs_placement_group_manager.h:228,
GcsHealthCheckManager gcs_health_check_manager.h, InternalKVManager
gcs_kv_manager.h). Redesigned: one asyncio RPC process holding plain
dict tables; health is heartbeat-lease based (nodes push state, the
sweeper declares death after `node_death_timeout_s`) instead of gRPC
ping; placement groups are placed centrally against the authoritative
resource view rather than via the reference's two-phase raylet commit.

Event feed: monotonically numbered events (node_added / node_dead /
actor_update / pg_update); clients poll `events_since` — the long-poll
pubsub of the reference (src/ray/pubsub/) collapsed to cursor polling.
"""

from __future__ import annotations

import argparse
import itertools
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from ray_tpu.cluster.lockstats import TimedRLock
from ray_tpu.cluster.rpc import NotPrimaryError, RpcServer
from ray_tpu.obs.telemetry import SLOThresholds, TelemetryStore
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.cluster.gcs")

_ha_metrics_cache: Optional[tuple] = None


def register_metrics() -> tuple:
    """Control-plane HA series (scripts/check_metrics.py hook).

    Plain process-registry metrics, NOT telemetry-plane aggregated: each
    GCS process (primary or standby) exports its own view — summing
    replication lag across roles would be meaningless."""
    global _ha_metrics_cache
    if _ha_metrics_cache is None:
        from ray_tpu.util.metrics import Counter, Gauge

        _ha_metrics_cache = (
            Gauge(
                "ray_tpu_gcs_replication_lag_seconds",
                description="how far the standby's replication-log tail "
                "trails the primary's mutation head (0 = fully caught up; "
                "measured at the long-poll ack on the primary and at the "
                "tail loop on the standby)",
            ),
            Counter(
                "ray_tpu_gcs_failovers_total",
                description="control-plane failovers: standby promotions "
                "to primary after the primary's lease expired",
            ),
        )
    return _ha_metrics_cache


@dataclass
class NodeEntry:
    node_id: str
    addr: tuple  # (host, port) of the node daemon
    resources: dict  # name -> total
    available: dict  # name -> available (as last reported)
    labels: dict = field(default_factory=dict)
    alive: bool = True
    draining: bool = False  # graceful drain: alive but not schedulable
    last_hb: float = field(default_factory=time.monotonic)
    pending: list = field(default_factory=list)  # queued lease specs
    # set on snapshot restore: the entry is a (possibly stale) claim, not
    # ground truth — the node's next heartbeat is answered with
    # `reregister` so it re-reports its live workers/actors/leases/PG
    # bundles and the reconcile path converges the table to reality
    pending_reconcile: bool = False


@dataclass
class ActorEntry:
    actor_id: bytes
    name: Optional[str]
    namespace: str
    node_id: Optional[str]
    worker_addr: Optional[tuple]
    state: str = "PENDING"  # PENDING | ALIVE | RESTARTING | DEAD
    max_restarts: int = 0
    num_restarts: int = 0
    # enough to re-create the actor elsewhere on node death
    creation_spec: Optional[bytes] = None
    owner_addr: Optional[tuple] = None
    lease_resources: dict = field(default_factory=lambda: {"num_cpus": 1})
    # the lease currently backing the actor's dedicated worker, and the
    # daemon that granted it — kill must release it THERE (reference:
    # GcsActorManager tracks the actor's leased worker per node)
    lease_id: Optional[str] = None
    node_addr: Optional[tuple] = None


class GcsService:
    """RPC handler. All methods take (payload, peer)."""

    def __init__(self, node_death_timeout_s: float = 5.0,
                 persist_path: Optional[str] = None,
                 role: str = "primary"):
        # one RLock domain serializes every table (the sharding roadmap's
        # bottleneck); TimedRLock feeds hold/wait histograms when
        # lockstats.enable_lock_timing() is on, raw-RLock cost otherwise
        self._lock = TimedRLock("gcs")
        # -- HA identity (cluster/ha.py) ----------------------------------
        # role/term/fenced under their own small lock so the RPC layer's
        # per-request ha_fence/ha_term checks never contend on the table
        # lock. Lock order: table lock OUTER, _ha_lock INNER — never the
        # reverse.
        self._ha_lock = threading.Lock()
        self._ha = {
            "role": role,
            "term": 0,
            "fenced": False,
            "failovers_total": 0,
            "fenced_writes_total": 0,
            "fenced_persists_total": 0,
        }
        # replication log: every critical mutation as (seq, term, op,
        # data), tailed by the warm standby over repl_since. Bounded like
        # the event ring; a tailer that falls off the retained window is
        # told to resync from a snapshot.
        self._repl: list[tuple[int, int, str, dict]] = []
        self._repl_seq = itertools.count(1)
        self._repl_head = 0
        self._repl_dropped = 0    # highest seq trimmed out of the log
        self._repl_acked = 0      # highest seq any tailer has consumed
        self._repl_synced_ts: Optional[float] = None
        self._events_dropped = -1  # highest event seq trimmed from the ring
        self._nodes: dict[str, NodeEntry] = {}
        self._actors: dict[bytes, ActorEntry] = {}
        self._named: dict[tuple, bytes] = {}  # (ns, name) -> actor_id
        self._pgs: dict[bytes, dict] = {}
        self._kv: dict[str, dict[bytes, bytes]] = {}
        self._objects: dict[bytes, set[str]] = {}  # obj_id -> node_ids
        self._events: list[tuple[int, str, dict]] = []
        self._event_seq = itertools.count()
        # push-tier pubsub: subscribers long-poll `events_since` with a
        # wait budget; _emit wakes them (reference: GCS pubsub push via
        # long-poll channels, src/ray/pubsub/publisher.h)
        self._events_cv = threading.Condition(self._lock)
        self._death_timeout = node_death_timeout_s
        self._pg_counter = itertools.count()
        # fault tolerance: durable snapshot of the control-plane tables
        # (reference: Redis-backed GCS storage, redis_store_client.h:107,
        # replayed by gcs_init_data.cc on restart). Nodes re-register via
        # the heartbeat "reregister" path; actor/PG/KV state comes back
        # from the snapshot.
        self._persist_path = persist_path
        self._dirty = 0
        self._persisted = 0
        self._persist_io = threading.Lock()  # serializes snapshot installs
        # control-plane FT observability (r13): restart + reconcile-delta
        # counters for `ray_tpu status` — a blackout must show up as a
        # counted restart and explicit convergence deltas, not as
        # phantom-zero metrics. restarts_total rides the snapshot so it
        # is cumulative across the process's own restarts.
        self.ft = {
            "gcs_restarts_total": 0,
            "reconcile_nodes_reregistered": 0,
            "reconcile_actors_confirmed": 0,
            "reconcile_actors_resurrected": 0,
            "reconcile_actors_lost": 0,
            "reconcile_bundles_adopted": 0,
            "reconcile_bundles_orphaned": 0,
            "reconcile_leases_reported": 0,
            "reconcile_actors_stale_copies": 0,
        }
        # snapshot-ALIVE actors awaiting confirmation by their node's
        # re-registration report; grace-expired leftovers are buried by
        # reconcile_sweep instead of lingering as phantoms
        self._needs_confirm: set[bytes] = set()
        self._orphan_bundles: list[tuple] = []  # (daemon_addr, pg_id, idx)
        # stale actor copies a reconciling node reported after the actor
        # was restarted elsewhere: (daemon_addr, actor_id, lease_id) to
        # destroy in reconcile_sweep (killing the lease kills the
        # dedicated worker and the copy with it)
        self._stale_copies: list[tuple] = []
        self._restore_t: Optional[float] = None
        # cluster-wide metrics plane (ray_tpu.obs.telemetry): bounded
        # time-series per (reporter, metric, labels), fed by heartbeat
        # piggybacks and dedicated telemetry_push RPCs. Deliberately NOT
        # persisted: metrics are a freshness surface; a restarted GCS
        # repopulates within one reporting interval.
        self.telemetry = TelemetryStore()
        # cluster-level KV prefix index (llm/kvtier): chain hash ->
        # {engine, tier, n_tokens}, fed by engine snapshots over
        # kvtier_update and consumed by prefix-aware routing. Like the
        # telemetry store it is deliberately NOT persisted — a restarted
        # GCS repopulates within one flush interval, and routers fall
        # back to the queue-depth ladder until it does. (The store lives
        # in cluster/prefix_index.py so the control plane never imports
        # the serving stack.)
        from ray_tpu.cluster.prefix_index import PrefixIndexStore

        self.prefix_index = PrefixIndexStore()
        if persist_path:
            self._load_snapshot()

    # -- persistence ----------------------------------------------------------

    def _mark_dirty(self) -> None:
        if self._persist_path:
            self._dirty += 1

    def _load_snapshot(self) -> None:
        import pickle

        t0 = time.time()
        try:
            with open(self._persist_path, "rb") as f:
                snap = pickle.load(f)
        except (OSError, pickle.UnpicklingError, EOFError):
            return
        self._actors = snap.get("actors", {})
        self._named = snap.get("named", {})
        self._pgs = snap.get("pgs", {})
        self._kv = snap.get("kv", {})
        self.ft["gcs_restarts_total"] = int(snap.get("restarts_total", 0)) + 1
        with self._ha_lock:
            # the fencing term is durable: a restarted primary must come
            # back AT its old term (still fenceable by a promoted standby),
            # never at term 0 where every zombie check would pass
            self._ha["term"] = max(self._ha["term"],
                                   int(snap.get("ha_term", 0)))
        # restored nodes are CLAIMS until they re-register: keep them
        # visible (their daemons are usually still alive and serving) but
        # answer their first heartbeat with `reregister` so the node
        # re-reports ground truth; the health sweep buries ones that
        # never come back within the death timeout
        for node_id, rec in snap.get("nodes", {}).items():
            self._nodes[node_id] = NodeEntry(
                node_id=node_id,
                addr=tuple(rec["addr"]),
                resources=dict(rec["resources"]),
                available=dict(rec["resources"]),
                labels=dict(rec.get("labels", {})),
                pending_reconcile=True,
            )
        self._needs_confirm = {
            a.actor_id for a in self._actors.values() if a.state == "ALIVE"
        }
        self._reserve_placed_bundles_locked()
        self._restore_t = time.monotonic()
        logger.info(
            "GCS restored from snapshot (restart #%d): %d actors, %d pgs, "
            "%d kv namespaces, %d nodes pending reconcile",
            self.ft["gcs_restarts_total"], len(self._actors), len(self._pgs),
            len(self._kv), len(self._nodes),
        )
        try:
            from ray_tpu.obs.recorder import get_recorder

            get_recorder().record(
                "gcs.restore", t0, time.time(),
                attrs={
                    "restart": str(self.ft["gcs_restarts_total"]),
                    "actors": str(len(self._actors)),
                    "pgs": str(len(self._pgs)),
                    "nodes": str(len(self._nodes)),
                },
            )
        except Exception:  # noqa: BLE001 — tracing must never break restore
            pass

    def _reserve_placed_bundles_locked(self) -> None:
        """Re-deduct placed PG bundles from restored nodes' availability.

        Restored/replicated nodes come back as reconcile claims with
        ``available = resources`` — the daemon's next full report is the
        ground truth that overwrites it. But until that report lands,
        placement would see inflated capacity and could double-book a
        fresh PG against bundles a CREATED group already holds on the
        node. Rebuild ``available`` as resources minus every placed
        bundle of a live group; the heartbeat's wholesale ``available``
        report converges any remaining drift."""
        for e in self._nodes.values():
            e.available = dict(e.resources)
        for pg in self._pgs.values():
            if pg["state"] not in ("CREATED", "RESCHEDULING"):
                continue
            for b in pg["bundles"]:
                node = self._nodes.get(b.get("node_id"))
                if node is None:
                    continue
                for k, v in b["resources"].items():
                    node.available[k] = node.available.get(k, 0.0) - v

    def _snapshot_state_locked(self) -> tuple[int, dict]:
        """(generation, shallow-copied durable tables). Caller holds the
        table lock — only the O(entries) dict copies happen under it;
        the pickle of the (potentially large) values runs outside, so a
        critical persist can't stretch the lock past what heartbeat
        handlers tolerate. Entries mutated after the copy may pickle
        torn across fields; the reconcile path converges those."""
        return self._dirty, {
            "actors": dict(self._actors),
            "named": dict(self._named),
            "pgs": {k: dict(v) for k, v in self._pgs.items()},
            # the collective rendezvous namespace is EPHEMERAL by design:
            # round contributions are multi-MB gradient payloads (every
            # write-ahead critical persist would ship them), and they are
            # gen-scoped in-flight state — after a restart the round is
            # gone, ranks surface typed CollectiveErrors within their
            # bounded waits, and the supervisor rides it out as a
            # blackout (re-form at gen+1, restore, resume)
            "kv": {ns: dict(kv) for ns, kv in self._kv.items()
                   if ns != "__collective__"},
            "nodes": {
                e.node_id: {
                    "addr": tuple(e.addr),
                    "resources": dict(e.resources),
                    "labels": dict(e.labels),
                }
                for e in self._nodes.values() if e.alive
            },
            "restarts_total": self.ft["gcs_restarts_total"],
            "ha_term": self.ha_term(),
        }

    def _write_snapshot(self, gen: int, doc: dict) -> None:
        """Crash-atomic snapshot install (.tmp + os.replace — the r12
        checkpoint discipline): a crash mid-write leaves the previous
        complete snapshot in place, never a torn file. Serialized by the
        persist I/O lock: handlers run on a thread pool, and two
        concurrent critical persists sharing one .tmp path could
        interleave writes or install an OLDER generation over a newer
        acked one — exactly the dirty window write-ahead exists to
        close. A generation at/behind what's already on disk is skipped
        (same-gen builds see identical tables)."""
        import pickle

        snap = pickle.dumps(doc)
        with self._persist_io:
            if gen <= self._persisted:
                return
            with self._ha_lock:
                if self._ha["fenced"]:
                    # a deposed zombie must NOT install snapshots: the
                    # promoted primary owns the durable state now, and a
                    # late persist here would resurrect pre-failover
                    # tables on the next restart (split-brain on disk)
                    self._ha["fenced_persists_total"] += 1
                    logger.warning(
                        "GCS fenced at term %d: snapshot persist rejected",
                        self._ha["term"],
                    )
                    return
            tmp = self._persist_path + ".tmp"
            try:
                with open(tmp, "wb") as f:
                    f.write(snap)
                    f.flush()
                    # fsync BEFORE the rename: os.replace is atomic in the
                    # namespace but says nothing about the DATA being on
                    # disk — without this, a power loss after the rename
                    # can leave the new name pointing at zero-length/torn
                    # content, which is exactly the loss the write-ahead
                    # ack (persist_critical) promised could not happen
                    os.fsync(f.fileno())
                os.replace(tmp, self._persist_path)
                # then fsync the directory so the rename itself is durable
                dfd = os.open(
                    os.path.dirname(os.path.abspath(self._persist_path)),
                    os.O_RDONLY,
                )
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
                self._persisted = gen
            except OSError:
                logger.exception("GCS snapshot write failed")

    def persist_if_dirty(self) -> None:
        """Debounced snapshot write (driven by the server's sweeper) —
        the non-critical tables' path. Critical mutations (actor/node
        registration, PG creation) go through persist_critical instead
        and never wait for this sweep."""
        if not self._persist_path:
            return
        with self._lock:
            if self._dirty == self._persisted:
                return
            gen, doc = self._snapshot_state_locked()
        self._write_snapshot(gen, doc)

    def persist_critical(self) -> None:
        """Write-ahead ack: persist NOW, before the caller's RPC is
        acknowledged. Closes the dirty window where an acked
        registration existed only in memory until the next debounced
        sweep — a crash in that window silently lost the actor."""
        if not self._persist_path:
            return
        with self._lock:
            gen, doc = self._snapshot_state_locked()
        self._write_snapshot(gen, doc)

    # -- HA: fencing term + replication log (cluster/ha.py) -------------------

    # methods a fenced/standby GCS still answers: diagnostics and the
    # replication plane itself (the standby must be able to tail a
    # primary that was just fenced, and status must stay queryable)
    _FENCE_EXEMPT = frozenset({
        "ha_status", "repl_since", "repl_snapshot", "gcs_ft",
        "telemetry_status", "telemetry_prometheus", "events_since",
    })
    # read-only methods: rejected when fenced (stale data) but not
    # counted as fenced WRITES — the split-brain acceptance gate counts
    # rejected mutations, not rejected reads
    _FENCE_READS = frozenset({
        "get_actor", "get_named_actor", "list_actors", "list_nodes",
        "list_pgs", "kv_get", "kv_keys", "kv_wait", "locate_object",
        "locate_many", "telemetry_slo", "telemetry_perf",
        "kvtier_lookup", "kvtier_stats", "cluster_demand",
        "autoscale_signals",
    })

    def ha_term(self) -> int:
        """Current fencing term — stamped into every RPC response by
        RpcServer._dispatch."""
        with self._ha_lock:
            return self._ha["term"]

    def ha_fence(self, hterm: int, method: str):
        """Envelope-level fencing check, called by RpcServer BEFORE the
        handler runs. A request carrying a term above ours proves a
        standby was promoted while we were alive: we are the zombie half
        of a split brain and must stop mutating. Returns None to admit
        the call, or the exception to answer with."""
        with self._ha_lock:
            if hterm > self._ha["term"]:
                if not self._ha["fenced"]:
                    logger.warning(
                        "GCS fenced: request carries term %d > own term %d "
                        "— a standby promoted; this process is a zombie",
                        hterm, self._ha["term"],
                    )
                self._ha["fenced"] = True
            if not self._ha["fenced"] or method in self._FENCE_EXEMPT:
                return None
            if method not in self._FENCE_READS:
                self._ha["fenced_writes_total"] += 1
            term = self._ha["term"]
        return NotPrimaryError(
            f"GCS fenced at term {term}: {method!r} rejected "
            f"(a newer primary holds term >= {hterm})",
            term=term,
        )

    def _repl_append_locked(self, op: str, data: dict) -> None:
        """Append one mutation to the replication log (caller holds the
        table lock) and wake long-polling tailers."""
        seq = next(self._repl_seq)
        with self._ha_lock:
            term = self._ha["term"]
        self._repl.append((seq, term, op, data))
        self._repl_head = seq
        if len(self._repl) > 20000:
            self._repl_dropped = self._repl[9999][0]
            del self._repl[:10000]
        self._events_cv.notify_all()

    def _repl_from_event_locked(self, kind: str, data: dict) -> None:
        """Translate an emitted event into a replication-log entry. The
        event stream says *something changed*; the log entry carries the
        full row so the standby can apply it without a read-back."""
        if kind == "actor_update":
            a = self._actors.get(data["actor_id"])
            if a is not None:
                self._repl_append_locked("actor_put", self._actor_info(a))
        elif kind == "node_added":
            e = self._nodes.get(data["node_id"])
            if e is not None:
                self._repl_append_locked("node_put", {
                    "node_id": e.node_id,
                    "addr": tuple(e.addr),
                    "resources": dict(e.resources),
                    "labels": dict(e.labels),
                })
        elif kind in ("node_dead", "node_draining"):
            self._repl_append_locked(kind, dict(data))
        elif kind == "pg_update":
            pg = self._pgs.get(data["pg_id"])
            if pg is None or pg["state"] == "REMOVED":
                self._repl_append_locked(
                    "pg_remove", {"pg_id": data["pg_id"]}
                )
            else:
                self._repl_append_locked("pg_put", self._pg_repl(pg))

    def _pg_repl(self, pg: dict) -> dict:
        """PG row as shipped on the replication log: the client-facing
        info plus the reserve bookkeeping a promoted standby needs to
        keep running the pg_reserve_sweep."""
        info = self._pg_info(pg)
        info["needs_reserve"] = bool(pg.get("needs_reserve"))
        info["reserve_gen"] = int(pg.get("reserve_gen", 0))
        return info

    def rpc_repl_since(self, payload, peer):
        """Replication-log long-poll: the standby's tail. Same cursor
        contract as events_since, plus the resync verdict — a tailer
        whose cursor fell off the retained window must re-bootstrap from
        repl_snapshot instead of silently skipping the gap."""
        cursor = int(payload["cursor"])
        wait = min(float(payload.get("wait", 0.0)), 10.0)
        deadline = time.monotonic() + wait
        with self._lock:
            if cursor <= self._repl_dropped:
                return {
                    "entries": [], "cursor": self._repl_head + 1,
                    "resync": True, "term": self.ha_term(),
                    "head": self._repl_head,
                }
            while True:
                out = [e for e in self._repl if e[0] >= cursor]
                if out or wait <= 0:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._events_cv.wait(remaining)
            next_cursor = out[-1][0] + 1 if out else cursor
            self._repl_acked = max(self._repl_acked, next_cursor - 1)
            if self._repl_acked >= self._repl_head:
                self._repl_synced_ts = time.monotonic()
            head = self._repl_head
        self._set_lag_metric_locked_free()
        return {
            "entries": out, "cursor": next_cursor, "resync": False,
            "term": self.ha_term(), "head": head,
        }

    def rpc_repl_snapshot(self, payload, peer):
        """Snapshot bootstrap/resync for a standby tailer: full durable
        tables + the cursor at which the log continues them."""
        with self._lock:
            _gen, doc = self._snapshot_state_locked()
            cursor = self._repl_head + 1
            self._repl_acked = self._repl_head
            self._repl_synced_ts = time.monotonic()
        return {"doc": doc, "cursor": cursor, "term": self.ha_term()}

    def _replication_lag_s(self) -> Optional[float]:
        """None = no tailer has ever synced; 0.0 = caught up; else the
        age of the last moment the tail was at head."""
        with self._lock:
            if self._repl_synced_ts is None:
                return None
            if self._repl_acked >= self._repl_head:
                return 0.0
            return time.monotonic() - self._repl_synced_ts

    def _set_lag_metric_locked_free(self) -> None:
        lag = self._replication_lag_s()
        if lag is not None:
            register_metrics()[0].set(lag)

    def rpc_ha_status(self, payload, peer):
        """Role/term/replication view for `ray_tpu status` and the HA
        tests: who is primary, at what term, how far any tailer trails."""
        with self._lock:
            head = self._repl_head
            acked = self._repl_acked
        with self._ha_lock:
            out = {
                "role": self._ha["role"],
                "term": self._ha["term"],
                "fenced": self._ha["fenced"],
                "failovers_total": self._ha["failovers_total"],
                "fenced_writes_total": self._ha["fenced_writes_total"],
                "fenced_persists_total": self._ha["fenced_persists_total"],
            }
        out["replication_lag_s"] = self._replication_lag_s()
        out["repl_head"] = head
        out["repl_acked"] = acked
        return out

    # -- HA: standby-side application + promotion -----------------------------

    def repl_install_snapshot(self, doc: dict, cursor: int, term: int) -> None:
        """Install a primary's snapshot wholesale (standby bootstrap or
        post-gap resync). Nodes come in as reconcile CLAIMS, exactly like
        a restart restore — on promotion their daemons re-register and
        ground truth converges."""
        with self._lock:
            self._actors = dict(doc.get("actors", {}))
            self._named = dict(doc.get("named", {}))
            self._pgs = {k: dict(v) for k, v in doc.get("pgs", {}).items()}
            self._kv = {ns: dict(kv) for ns, kv in doc.get("kv", {}).items()}
            self.ft["gcs_restarts_total"] = int(doc.get("restarts_total", 0))
            self._nodes = {}
            for node_id, rec in doc.get("nodes", {}).items():
                self._nodes[node_id] = NodeEntry(
                    node_id=node_id,
                    addr=tuple(rec["addr"]),
                    resources=dict(rec["resources"]),
                    available=dict(rec["resources"]),
                    labels=dict(rec.get("labels", {})),
                    pending_reconcile=True,
                )
            with self._ha_lock:
                self._ha["term"] = max(
                    self._ha["term"], int(term), int(doc.get("ha_term", 0))
                )
            self._mark_dirty()

    def repl_apply(self, entries) -> int:
        """Apply tailed log entries in order; observes each entry's term
        so the standby's own term never trails the primary's."""
        applied = 0
        with self._lock:
            for _seq, term, op, data in entries:
                self._repl_apply_one_locked(op, data)
                with self._ha_lock:
                    if term > self._ha["term"]:
                        self._ha["term"] = int(term)
                applied += 1
            if applied:
                self._mark_dirty()
        return applied

    def _repl_apply_one_locked(self, op: str, data: dict) -> None:
        if op == "actor_put":
            aid = data["actor_id"]
            a = ActorEntry(
                actor_id=aid,
                name=data.get("name"),
                namespace=data.get("namespace", "default"),
                node_id=data.get("node_id"),
                worker_addr=tuple(data["worker_addr"])
                if data.get("worker_addr") else None,
                state=data.get("state", "PENDING"),
                max_restarts=int(data.get("max_restarts", 0)),
                num_restarts=int(data.get("num_restarts", 0)),
                creation_spec=data.get("creation_spec"),
                owner_addr=tuple(data["owner_addr"])
                if data.get("owner_addr") else None,
                lease_resources=dict(
                    data.get("lease_resources") or {"num_cpus": 1}
                ),
                lease_id=data.get("lease_id"),
                node_addr=tuple(data["node_addr"])
                if data.get("node_addr") else None,
            )
            self._actors[aid] = a
            if a.name:
                self._named[(a.namespace, a.name)] = aid
        elif op == "node_put":
            self._nodes[data["node_id"]] = NodeEntry(
                node_id=data["node_id"],
                addr=tuple(data["addr"]),
                resources=dict(data["resources"]),
                available=dict(data["resources"]),
                labels=dict(data.get("labels", {})),
                pending_reconcile=True,
            )
        elif op == "node_dead":
            e = self._nodes.get(data["node_id"])
            if e is not None:
                e.alive = False
        elif op == "node_draining":
            e = self._nodes.get(data["node_id"])
            if e is not None:
                e.draining = True
        elif op == "pg_put":
            self._pgs[data["pg_id"]] = {
                "pg_id": data["pg_id"],
                "bundles": [dict(b) for b in data["bundles"]],
                "strategy": data["strategy"],
                "state": data["state"],
                "name": data.get("name"),
                "needs_reserve": bool(data.get("needs_reserve")),
                "reserve_gen": int(data.get("reserve_gen", 0)),
            }
        elif op == "pg_remove":
            self._pgs.pop(data["pg_id"], None)
        elif op == "kv_put":
            self._kv.setdefault(data["ns"], {})[data["key"]] = data["value"]
            self._events_cv.notify_all()
        elif op == "kv_del":
            self._kv.get(data["ns"], {}).pop(data["key"], None)
        # unknown ops are skipped: forward compatibility with a newer
        # primary shipping ops this standby build doesn't know

    def promote(self, term: Optional[int] = None) -> int:
        """Standby -> primary. Bumps the fencing term past everything
        seen, then runs the r13 restart-restore discipline over the
        replicated tables: every node becomes a reconcile claim with a
        fresh heartbeat lease, every ALIVE actor awaits confirmation, and
        the grace clock starts — the reconcile sweep converges whatever
        the log missed. Persists critically so the new term is durable
        before the first client is acked at it."""
        with self._lock:
            with self._ha_lock:
                new_term = max(self._ha["term"] + 1, int(term or 0))
                self._ha["term"] = new_term
                self._ha["role"] = "primary"
                self._ha["fenced"] = False
                self._ha["failovers_total"] += 1
            now = time.monotonic()
            for e in self._nodes.values():
                e.pending_reconcile = True
                e.last_hb = now  # fresh lease: death clock starts NOW
            self._needs_confirm = {
                a.actor_id for a in self._actors.values()
                if a.state == "ALIVE"
            }
            self._reserve_placed_bundles_locked()
            self._restore_t = now
            self._mark_dirty()
            self._events_cv.notify_all()
        self.persist_critical()
        register_metrics()[1].inc()
        logger.warning(
            "GCS standby PROMOTED to primary at term %d (%d nodes pending "
            "reconcile, %d actors pending confirm)",
            new_term, len(self._nodes), len(self._needs_confirm),
        )
        return new_term

    # -- events ---------------------------------------------------------------

    def _emit(self, kind: str, data: dict) -> None:
        self._events.append((next(self._event_seq), kind, data))
        if len(self._events) > 10000:
            self._events_dropped = self._events[4999][0]
            del self._events[:5000]
        # critical mutations surface as events; mirror them onto the
        # replication log (full-row entries) before waking subscribers
        self._repl_from_event_locked(kind, data)
        self._events_cv.notify_all()

    def rpc_events_since(self, payload, peer):
        """Cursor'd event feed. With `wait` > 0 this is a long-poll: the
        handler thread parks until an event at/after `cursor` lands or
        the wait budget expires — push-latency delivery without a
        persistent subscriber channel (reference: GCS pubsub long-poll,
        src/ray/pubsub/publisher.h).

        A `resync: true` verdict means the cursor fell below the oldest
        retained event (the ring trimmed past it): events were LOST to
        this subscriber, and anything mirroring state off the feed must
        rebuild from a full read instead of continuing the tail."""
        cursor = payload["cursor"]
        # cap well below RpcClient's 30s default call timeout: a quiet
        # feed must answer (empty) before the client gives up on the RPC
        wait = min(float(payload.get("wait", 0.0)), 10.0)
        deadline = time.monotonic() + wait
        with self._lock:
            if cursor <= self._events_dropped:
                next_cursor = (
                    self._events[0][0] if self._events
                    else self._events_dropped + 1
                )
                return {"events": [], "cursor": next_cursor, "resync": True}
            while True:
                out = [e for e in self._events if e[0] >= cursor]
                if out or wait <= 0:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._events_cv.wait(remaining)
            next_cursor = self._events[-1][0] + 1 if self._events else cursor
        return {"events": out, "cursor": next_cursor, "resync": False}

    # -- nodes ----------------------------------------------------------------

    def rpc_register_node(self, payload, peer):
        with self._lock:
            e = NodeEntry(
                node_id=payload["node_id"],
                addr=tuple(payload["addr"]),
                resources=dict(payload["resources"]),
                available=dict(payload["resources"]),
                labels=payload.get("labels", {}),
            )
            self._nodes[e.node_id] = e
            # re-registration after a GCS restart rebuilds the object
            # directory from the node's own inventory (the reference
            # relearns locations via raylet resubscription)
            for oid in payload.get("objects", ()):
                self._objects.setdefault(oid, set()).add(e.node_id)
            if "actors" in payload or "bundles" in payload:
                # report-carrying registration = a RE-registration (fresh
                # nodes never send reports) — counted even when the
                # snapshot didn't know the node (lost/stale snapshot)
                self.ft["reconcile_nodes_reregistered"] += 1
            # reconcile-on-restart: converge the (possibly stale) snapshot
            # to the node's reported ground truth — live actors are
            # confirmed or resurrected, never killed; reported bundle
            # reservations are adopted, never double-reserved; a
            # snapshot-ALIVE actor this node did NOT report is gone and
            # takes the normal node-death path (restart budget or bury)
            if "actors" in payload or "bundles" in payload:
                self._reconcile_node_report_locked(e, payload)
            self._mark_dirty()
            self._emit("node_added", {"node_id": e.node_id, "addr": e.addr})
            logger.info("node %s registered at %s", e.node_id, e.addr)
        # node registration is a critical mutation: persist BEFORE the ack
        # (write-ahead) so a crash right after cannot forget the node
        self.persist_critical()
        return {"ok": True}

    def _reconcile_node_report_locked(self, e: NodeEntry, payload) -> None:
        """Apply a re-registering node's {actors, leases, bundles} report
        (caller holds the lock) — the r09 pg_reserve_sweep generalized
        into full reconciliation."""
        reported: set[bytes] = set()
        for rec in payload.get("actors", ()):
            aid = rec["actor_id"]
            reported.add(aid)
            a = self._actors.get(aid)
            if a is not None and a.state == "DEAD":
                # tombstone wins: the kill was acked; a worker whose
                # destroy raced the outage must not resurrect it
                continue
            if a is None:
                # created after the last snapshot (or the snapshot was
                # lost): the data plane is ground truth — resurrect
                a = ActorEntry(
                    actor_id=aid,
                    name=rec.get("name"),
                    namespace=rec.get("namespace", "default"),
                    node_id=e.node_id,
                    worker_addr=tuple(rec["worker_addr"])
                    if rec.get("worker_addr") else None,
                    state="ALIVE",
                    max_restarts=int(rec.get("max_restarts", 0)),
                    creation_spec=rec.get("creation_spec"),
                    lease_resources=dict(
                        rec.get("lease_resources") or {"num_cpus": 1}
                    ),
                    lease_id=rec.get("lease_id"),
                    node_addr=e.addr,
                )
                self._actors[aid] = a
                if a.name and (a.namespace, a.name) not in self._named:
                    self._named[(a.namespace, a.name)] = aid
                self.ft["reconcile_actors_resurrected"] += 1
                self._emit("actor_update", {"actor_id": aid, "state": "ALIVE"})
            else:
                cur = self._nodes.get(a.node_id) if a.node_id else None
                if (
                    a.state == "ALIVE"
                    and a.node_id is not None
                    and a.node_id != e.node_id
                    and cur is not None and cur.alive
                    and not cur.pending_reconcile
                ):
                    # the table's binding is NEWER ground truth: this
                    # actor was already restarted on another live node
                    # (e.g. while the reporter was partitioned and
                    # declared dead). Repointing here would leave two
                    # live copies — instead the reported stale copy is
                    # destroyed by the reconcile sweep
                    self._stale_copies.append(
                        (e.addr, aid, rec.get("lease_id"))
                    )
                    self.ft["reconcile_actors_stale_copies"] += 1
                    continue
                a.state = "ALIVE"
                a.node_id = e.node_id
                if rec.get("worker_addr"):
                    a.worker_addr = tuple(rec["worker_addr"])
                if rec.get("lease_id"):
                    a.lease_id = rec["lease_id"]
                a.node_addr = e.addr
                self.ft["reconcile_actors_confirmed"] += 1
                # no event fires for a silent confirm, but the binding
                # (node/worker/lease) may have changed: replicate it
                self._repl_append_locked("actor_put", self._actor_info(a))
            self._needs_confirm.discard(aid)
        # snapshot-ALIVE actors homed on THIS node that it did not report
        # are gone with the outage: normal node-death treatment, now
        for a in self._actors.values():
            if (
                a.actor_id in self._needs_confirm
                and a.node_id == e.node_id
                and a.actor_id not in reported
            ):
                self._needs_confirm.discard(a.actor_id)
                self.ft["reconcile_actors_lost"] += 1
                self._bury_or_restart_locked(a)
        for rec in payload.get("bundles", ()):
            pg = self._pgs.get(rec["pg_id"])
            idx = int(rec["bundle_index"])
            if (
                pg is None or pg["state"] == "REMOVED"
                or idx >= len(pg["bundles"])
            ):
                # reservation for a PG the table no longer knows: the
                # daemon still holds the resources — release them via the
                # reconcile sweep (needs the RPC pool, not held here)
                self._orphan_bundles.append((e.addr, rec["pg_id"], idx))
                self.ft["reconcile_bundles_orphaned"] += 1
                continue
            b = pg["bundles"][idx]
            b["node_id"] = e.node_id  # daemon-held reservation wins
            self.ft["reconcile_bundles_adopted"] += 1
            self._repl_append_locked("pg_put", self._pg_repl(pg))
        self.ft["reconcile_leases_reported"] += len(payload.get("leases", ()))

    def _bury_or_restart_locked(self, a: ActorEntry) -> None:
        """Node-death treatment for one actor (caller holds the lock)."""
        if a.state not in ("ALIVE", "PENDING"):
            return
        if a.num_restarts < a.max_restarts:
            a.state = "RESTARTING"
            a.num_restarts += 1
            a.node_id = None
            a.worker_addr = None
        else:
            a.state = "DEAD"
        self._emit(
            "actor_update",
            {"actor_id": a.actor_id, "state": a.state,
             "num_restarts": a.num_restarts},
        )

    def _heartbeat_locked(self, payload) -> dict:
        """Table-side of one heartbeat; caller holds ``self._lock``.
        Telemetry piggybacks are the CALLER's job (outside the table
        lock: the store has its own) — and only for accepted beats, so a
        node told to re-register never sneaks metrics in under a stale
        registration."""
        e = self._nodes.get(payload["node_id"])
        if e is None or not e.alive:
            # unknown/dead node: tell it to re-register (GCS restart or
            # it was declared dead while partitioned)
            return {"ok": False, "reregister": True}
        if e.pending_reconcile:
            # restored-from-snapshot claim: keep the lease fresh (the
            # node IS alive — it just proved it) but demand a full
            # re-registration so its ground-truth report arrives
            e.last_hb = time.monotonic()
            return {"ok": False, "reregister": True}
        e.last_hb = time.monotonic()
        if "available" in payload:
            e.available = dict(payload["available"])
        e.pending = list(payload.get("pending", ()))
        if payload.get("draining") and not e.draining:
            e.draining = True
            self._emit("node_draining", {"node_id": e.node_id})
        return {"ok": True}

    def rpc_heartbeat(self, payload, peer):
        with self._lock:
            out = self._heartbeat_locked(payload)
        snap = payload.get("telemetry")
        if snap and out.get("ok"):
            # piggybacked metrics snapshot (outside the table lock: the
            # store has its own); a STALL_HEARTBEAT partition shows up as
            # telemetry staleness for exactly the stalled node
            self.telemetry.ingest(
                payload["node_id"], snap, {"kind": "node"}
            )
        return out

    def rpc_heartbeat_batch(self, payload, peer):
        """Coalesced heartbeat frame (r20 control-plane batching): N
        heartbeats under ONE table-lock acquisition, their telemetry
        piggybacks under ONE store-lock acquisition
        (TelemetryStore.ingest_batch). Per-beat semantics — reregister
        demands, draining transitions, stale-seq drops — are identical
        to N individual ``heartbeat`` calls; results keep frame order."""
        beats = list(payload.get("heartbeats", ()))
        with self._lock:
            results = [self._heartbeat_locked(hb) for hb in beats]
        telem = [
            (hb["node_id"], hb["telemetry"], {"kind": "node"})
            for hb, r in zip(beats, results)
            if r.get("ok") and hb.get("telemetry")
        ]
        if telem:
            self.telemetry.ingest_batch(telem)
        return {"ok": True, "results": results}

    # -- telemetry plane ------------------------------------------------------

    def rpc_telemetry_push(self, payload, peer):
        """Dedicated push path for engine hosts / serving processes (node
        daemons piggyback on heartbeats instead). Drops/delays of this
        RPC may only cost freshness: snapshots carry monotonic totals."""
        return self.telemetry.ingest(
            payload["reporter_id"],
            payload["snapshot"],
            {"kind": payload.get("kind", ""), "role": payload.get("role", "")},
        )

    def rpc_telemetry_push_batch(self, payload, peer):
        """Coalesced telemetry frame: N reporter snapshots under one
        store-lock acquisition. Same drop/stale semantics as N pushes."""
        items = [
            (
                p["reporter_id"], p["snapshot"],
                {"kind": p.get("kind", ""), "role": p.get("role", "")},
            )
            for p in payload.get("pushes", ())
        ]
        return {"ok": True, "results": self.telemetry.ingest_batch(items)}

    # ops a coalesced control-plane frame may carry: the high-rate small
    # RPCs. Long-polls (kv_wait, events_since) and anything that can
    # park a waiter are excluded — a frame must never block mid-dispatch.
    _BATCHABLE = frozenset({
        "heartbeat", "telemetry_push", "kv_put", "kv_get", "kv_del",
        "kv_keys", "cluster_demand", "kvtier_update", "kvtier_lookup",
        "locate_object", "add_object_location", "remove_object_location",
    })

    def rpc_batch(self, payload, peer):
        """Generic coalesced frame: dispatch N whitelisted ops in one
        RPC, coalescing the ingest-heavy kinds (heartbeats share one
        table-lock acquisition, telemetry snapshots one store-lock
        acquisition). Per-op results keep frame order; an unknown or
        non-batchable method yields an error entry, never a dropped
        frame."""
        ops = list(payload.get("ops", ()))
        results: list = [None] * len(ops)
        hb_idx = [
            i for i, op in enumerate(ops)
            if op.get("method") == "heartbeat"
        ]
        if hb_idx:
            with self._lock:
                for i in hb_idx:
                    results[i] = self._heartbeat_locked(
                        ops[i].get("payload") or {}
                    )
        telem: list = []        # (reporter_id, snapshot, meta) to ingest
        telem_slot: list = []   # result index to receive the outcome (or None)
        for i, op in enumerate(ops):
            method = op.get("method", "")
            body = op.get("payload") or {}
            if method == "heartbeat":
                snap = body.get("telemetry")
                if snap and results[i].get("ok"):
                    # piggyback outcome stays folded into the heartbeat
                    # result, same as the unbatched path
                    telem.append((body["node_id"], snap, {"kind": "node"}))
                    telem_slot.append(None)
                continue
            if method == "telemetry_push":
                telem.append((
                    body["reporter_id"], body["snapshot"],
                    {"kind": body.get("kind", ""),
                     "role": body.get("role", "")},
                ))
                telem_slot.append(i)
                continue
            if method not in self._BATCHABLE:
                results[i] = {
                    "ok": False, "error": f"not batchable: {method!r}",
                }
                continue
            try:
                results[i] = getattr(self, f"rpc_{method}")(body, peer)
            except Exception as e:  # noqa: BLE001 — per-op isolation
                results[i] = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        if telem:
            for slot, out in zip(telem_slot,
                                 self.telemetry.ingest_batch(telem)):
                if slot is not None:
                    results[slot] = out
        return {"ok": True, "results": results}

    def rpc_telemetry_cluster(self, payload, peer):
        """GCS-aggregated cluster metrics (ClusterClient.cluster_metrics
        and the dashboard's /api/metrics). Dropped by accident when the
        r20 batching rework reshuffled the telemetry handlers — the
        store-side aggregation was always there, the RPC surface wasn't."""
        return self.telemetry.cluster_metrics()

    def rpc_telemetry_slo(self, payload, peer):
        th = SLOThresholds.from_dict((payload or {}).get("thresholds"))
        return self.telemetry.slo_report(th)

    def rpc_telemetry_prometheus(self, payload, peer):
        return self.telemetry.prometheus_text()

    def rpc_telemetry_perf(self, payload, peer):
        """Sampled-profiling rollup (obs.perfwatch): per-step times,
        coverage, MFU, overlap, regression grades — the dashboard
        /api/perf surface."""
        return self.telemetry.perf_health()

    def rpc_telemetry_status(self, payload, peer):
        """One-query cluster status (scripts/ray_tpu_status.py): node
        table + reporters + pool rollups + utilization + SLO grades +
        control-plane FT counters (restart/reconcile deltas — a blackout
        shows as a counted restart, not phantom-zero metrics)."""
        th = SLOThresholds.from_dict((payload or {}).get("thresholds"))
        out = {"nodes": self.rpc_list_nodes(None, peer)}
        out.update(self.telemetry.status_payload(th))
        out["gcs_ft"] = self.rpc_gcs_ft(None, peer)
        out["gcs_ha"] = self.rpc_ha_status(None, peer)
        out["kvtier_index"] = self.prefix_index.stats()
        return out

    def rpc_autoscale_signals(self, payload, peer):
        """ONE RPC with everything the r20 PoolAutoscaler consumes:
        per-tag SLO grades + autoscaler_hints, pool rollups, queue
        depth, the measured prefill-span distribution, per-reporter
        staleness — plus the pending lease demand the seed autoscaler
        fed on (the surviving input of the retired second brain)."""
        th = SLOThresholds.from_dict((payload or {}).get("thresholds"))
        out = self.telemetry.autoscale_signals(th)
        with self._lock:
            out["pending_demand"] = sum(
                1
                for e in self._nodes.values()
                if e.alive
                for _spec in getattr(e, "pending", ())
            )
        return out

    def rpc_kvtier_update(self, payload, peer):
        """One engine's prefix-index snapshot (epoch-banked, seq-guarded:
        stale or replayed snapshots are dropped, never merged)."""
        return self.prefix_index.update(payload)

    def rpc_kvtier_lookup(self, payload, peer):
        """Longest indexed prefix per engine over the request's chain
        hashes — the prefix-aware routing signal. Engines with stale
        snapshots are omitted: a router seeing nothing falls back to
        its queue-depth/p2c ladder."""
        return self.prefix_index.lookup((payload or {}).get("hashes", []))

    def rpc_kvtier_drop(self, payload, peer):
        """Remove one engine's rows outright (orderly teardown). A
        crashed engine that never calls this is reaped by the store's
        expire horizon instead."""
        self.prefix_index.drop_engine(str((payload or {}).get("engine", "")))
        return {"ok": True}

    def rpc_kvtier_stats(self, payload, peer):
        return self.prefix_index.stats()

    def rpc_gcs_ft(self, payload, peer):
        """Control-plane FT counters: restarts + reconcile deltas (the
        bench's duplicate/lost-actor gate reads these), plus the HA
        failover/fence counters."""
        with self._lock:
            out = dict(self.ft)
            out["actors_pending_confirm"] = len(self._needs_confirm)
        with self._ha_lock:
            out["gcs_failovers_total"] = self._ha["failovers_total"]
            out["gcs_fenced_writes_total"] = self._ha["fenced_writes_total"]
            out["gcs_fenced_persists_total"] = self._ha["fenced_persists_total"]
        return out

    def rpc_cluster_demand(self, payload, peer):
        """Aggregate autoscaling view: per-node capacity plus every lease
        spec currently parked in a daemon's server-side queue (reference:
        resource demand aggregation the GCS feeds the autoscaler)."""
        with self._lock:
            return {
                "nodes": [
                    {
                        "node_id": e.node_id,
                        "resources": dict(e.resources),
                        "available": dict(e.available),
                        "alive": e.alive,
                    }
                    for e in self._nodes.values()
                ],
                "pending": [
                    spec
                    for e in self._nodes.values()
                    if e.alive
                    for spec in getattr(e, "pending", ())
                ],
            }

    def rpc_drain_node(self, payload, peer):
        """Graceful removal (cluster_utils teardown)."""
        with self._lock:
            self._mark_dead(payload["node_id"], reason="drained")
        return {"ok": True}

    def rpc_list_nodes(self, payload, peer):
        with self._lock:
            return [
                {
                    "node_id": e.node_id,
                    "addr": e.addr,
                    "resources": dict(e.resources),
                    "available": dict(e.available),
                    "labels": dict(e.labels),
                    "alive": e.alive,
                    "draining": e.draining,
                }
                for e in self._nodes.values()
            ]

    def _mark_dead(self, node_id: str, reason: str) -> None:
        e = self._nodes.get(node_id)
        if e is None or not e.alive:
            return
        e.alive = False
        logger.warning("node %s declared dead (%s)", node_id, reason)
        self._emit("node_dead", {"node_id": node_id, "reason": reason})
        # objects whose only copy was there are lost
        for oid, locs in list(self._objects.items()):
            locs.discard(node_id)
            if not locs:
                del self._objects[oid]
        # actors on that node: restart or bury (reference:
        # GcsActorManager::OnNodeDead)
        for a in self._actors.values():
            if a.node_id == node_id and a.state in ("ALIVE", "PENDING"):
                self._needs_confirm.discard(a.actor_id)
                self._bury_or_restart_locked(a)
        self._mark_dirty()
        # placement groups with bundles there reschedule
        for pg in self._pgs.values():
            if any(b.get("node_id") == node_id for b in pg["bundles"]):
                for b in pg["bundles"]:
                    if b.get("node_id") == node_id:
                        b["node_id"] = None
                pg["state"] = "RESCHEDULING"
                self._try_place_pg(pg)
                self._emit("pg_update", {"pg_id": pg["pg_id"], "state": pg["state"]})

    def health_sweep(self) -> None:
        with self._lock:
            now = time.monotonic()
            for e in list(self._nodes.values()):
                if e.alive and now - e.last_hb > self._death_timeout:
                    self._mark_dead(e.node_id, reason="heartbeat timeout")

    def restart_sweep(self, pool) -> None:
        """Re-create RESTARTING actors on surviving nodes (reference:
        GcsActorScheduler re-leases a worker for restartable actors)."""
        from ray_tpu.cluster.rpc import RemoteError, RpcError

        with self._lock:
            todo = [
                a for a in self._actors.values()
                if a.state == "RESTARTING" and a.creation_spec is not None
            ]
            nodes = [
                (e.node_id, e.addr, dict(e.available))
                for e in self._nodes.values() if e.alive and not e.draining
            ]
        for a in todo:
            res = a.lease_resources
            for node_id, addr, avail in nodes:
                if not all(avail.get(k, 0.0) >= v for k, v in res.items()):
                    continue
                try:
                    daemon = pool.get(tuple(addr))
                    r = daemon.call(
                        "request_worker_lease", {"resources": res}, timeout=60
                    )
                    if "grant" not in r:
                        continue
                    g = r["grant"]
                    w = pool.get(tuple(g["worker_addr"]))
                    cr = w.call(
                        "create_actor",
                        {"actor_id": a.actor_id, "creation_spec": a.creation_spec,
                         "meta": {"name": a.name, "namespace": a.namespace,
                                  "max_restarts": a.max_restarts,
                                  "lease_resources": dict(a.lease_resources)}},
                        timeout=300,
                    )
                    if not cr.get("ok"):
                        daemon.call(
                            "release_lease",
                            {"lease_id": g["lease_id"], "kill": True},
                            timeout=10,
                        )
                        logger.warning(
                            "actor %s restart failed: %s",
                            a.actor_id.hex()[:12], cr.get("error"),
                        )
                        continue
                    with self._lock:
                        if (
                            a.state == "ALIVE"
                            and a.worker_addr is not None
                            and tuple(a.worker_addr) != tuple(g["worker_addr"])
                        ):
                            # a reconcile report confirmed the ORIGINAL
                            # copy alive while this sweep was re-creating
                            # it (restore race): keep ground truth, kill
                            # the just-created duplicate with its lease
                            duplicate = True
                        else:
                            duplicate = False
                            a.node_id = g["node_id"]
                            a.worker_addr = tuple(g["worker_addr"])
                            a.lease_id = g["lease_id"]
                            a.node_addr = tuple(g.get("node_addr") or addr)
                            a.state = "ALIVE"
                            self._mark_dirty()
                            self._emit(
                                "actor_update",
                                {"actor_id": a.actor_id, "state": "ALIVE",
                                 "worker_addr": a.worker_addr},
                            )
                    if duplicate:
                        daemon.call(
                            "release_lease",
                            {"lease_id": g["lease_id"], "kill": True},
                            timeout=10,
                        )
                        logger.warning(
                            "actor %s: reconcile confirmed the original "
                            "copy; discarded duplicate restart",
                            a.actor_id.hex()[:12],
                        )
                        break
                    logger.info(
                        "actor %s restarted on %s",
                        a.actor_id.hex()[:12], g["node_id"],
                    )
                    break
                except (RpcError, RemoteError):
                    continue

    def reconcile_sweep(self, pool) -> None:
        """Post-restore convergence work that needs the RPC pool:

         * release orphaned bundle reservations a re-registering node
           reported for PGs the table no longer knows (their resources
           are otherwise leaked on the daemon forever);
         * after a grace period, bury snapshot-ALIVE actors whose node
           never re-registered to confirm them (the node itself is
           handled by the health sweep; this covers actors whose
           snapshot node entry was missing or stale)."""
        from ray_tpu.cluster.rpc import RemoteError, RpcError

        with self._lock:
            orphans, self._orphan_bundles = self._orphan_bundles, []
            stale, self._stale_copies = self._stale_copies, []
        for addr, pg_id, idx in orphans:
            try:
                pool.get(tuple(addr)).call(
                    "release_pg_bundle",
                    {"pg_id": pg_id, "bundle_index": idx},
                    timeout=10,
                )
            except (RpcError, RemoteError):
                pass  # daemon died; the reservation died with it
        for addr, aid, lease_id in stale:
            # kill the stale copy's lease on its own daemon: the worker
            # (and the duplicate actor in it) dies with the lease
            if not lease_id:
                continue
            try:
                pool.get(tuple(addr)).call(
                    "release_lease", {"lease_id": lease_id, "kill": True},
                    timeout=10,
                )
                logger.warning(
                    "reconcile: destroyed stale copy of actor %s",
                    aid.hex()[:12] if isinstance(aid, bytes) else aid,
                )
            except (RpcError, RemoteError):
                pass
        grace = max(2 * self._death_timeout, 3.0)
        with self._lock:
            # invariant: _needs_confirm is only read/cleared under _lock —
            # the restore path populates it concurrently with this sweep
            if self._restore_t is None or not self._needs_confirm:
                return
            if time.monotonic() - self._restore_t < grace:
                return
            stale, self._needs_confirm = self._needs_confirm, set()
            for aid in stale:
                a = self._actors.get(aid)
                if a is None or a.state not in ("ALIVE", "PENDING"):
                    continue
                node = self._nodes.get(a.node_id)
                if node is not None and node.alive and not node.pending_reconcile:
                    continue  # node re-registered and confirmed it already
                self.ft["reconcile_actors_lost"] += 1
                self._bury_or_restart_locked(a)
            if stale:
                self._mark_dirty()

    def pg_reserve_sweep(self, pool) -> None:
        """Reserve re-placed placement-group bundles on their new nodes
        (reference: the raylet-side two-phase commit the reference replays
        on reschedule). The daemon's reserve is idempotent by
        (pg_id, bundle_index), so surviving bundles are no-ops."""
        from ray_tpu.cluster.rpc import RemoteError, RpcError

        with self._lock:
            # snapshot bundles AND the placement generation under the
            # lock: the reserve RPCs below run lock-free, and a node
            # death mid-sweep re-places these same bundle dicts
            todo = [
                (pg, pg.get("reserve_gen", 0),
                 [(dict(b["resources"]), b.get("node_id"))
                  for b in pg["bundles"]])
                for pg in self._pgs.values()
                if pg.get("needs_reserve") and pg["state"] == "CREATED"
            ]
            nodes = {
                e.node_id: e.addr for e in self._nodes.values() if e.alive
            }
        for pg, gen, bundles in todo:
            all_ok = True
            for i, (res, node_id) in enumerate(bundles):
                addr = nodes.get(node_id)
                if addr is None:
                    all_ok = False
                    continue
                try:
                    r = pool.get(tuple(addr)).call(
                        "reserve_pg_bundle",
                        {"pg_id": pg["pg_id"], "bundle_index": i,
                         "resources": res},
                        timeout=10,
                    )
                    if not r.get("ok"):
                        all_ok = False
                except (RpcError, RemoteError):
                    all_ok = False
            if all_ok:
                with self._lock:
                    # clear ONLY if no re-placement raced the RPCs: a
                    # fresh needs_reserve (bumped generation) must survive
                    # or its bundles stay unleasable forever
                    if pg.get("reserve_gen", 0) == gen \
                            and pg["state"] == "CREATED":
                        pg["needs_reserve"] = False
                        self._mark_dirty()  # re-reservation is durable state
                logger.info(
                    "pg %s re-reserved after reschedule",
                    pg["pg_id"].hex()[:12] if isinstance(pg["pg_id"], bytes)
                    else pg["pg_id"],
                )

    # -- kv -------------------------------------------------------------------

    def rpc_kv_put(self, payload, peer):
        with self._lock:
            ns_name = payload.get("ns", "default")
            ns = self._kv.setdefault(ns_name, {})
            if payload.get("nx") and payload["key"] in ns:
                # set-if-absent: atomic claim primitive (job submission
                # ids, leader election) — check-then-put at the caller
                # races between clients
                return {"ok": False}
            ns[payload["key"]] = payload["value"]
            self._mark_dirty()
            if ns_name != "__collective__":
                # the collective rendezvous namespace is ephemeral and
                # multi-MB (see _snapshot_state_locked) — everything else
                # replicates so a promoted standby serves the same KV
                self._repl_append_locked("kv_put", {
                    "ns": ns_name, "key": payload["key"],
                    "value": payload["value"],
                })
            self._events_cv.notify_all()  # wake kv_wait long-pollers
        return {"ok": True}

    def rpc_kv_wait(self, payload, peer):
        """Long-poll kv_get: park until `key` appears (or the wait budget
        expires) and return its value (None on timeout). Per-call wait is
        capped low so a fully parked handler pool self-heals; callers loop
        to their own deadline. This is the synchronization primitive the
        cluster-tier collectives rendezvous on (reference analog: Redis
        BLPOP-style waits in the GCS store client)."""
        deadline = time.monotonic() + min(float(payload.get("wait", 1.0)), 5.0)
        ns_name = payload.get("ns", "default")
        key = payload["key"]
        with self._lock:
            while True:
                v = self._kv.get(ns_name, {}).get(key)
                if v is not None:
                    return v
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._events_cv.wait(remaining)

    def rpc_kv_get(self, payload, peer):
        with self._lock:
            return self._kv.get(payload.get("ns", "default"), {}).get(payload["key"])

    def rpc_kv_del(self, payload, peer):
        with self._lock:
            ns_name = payload.get("ns", "default")
            self._kv.get(ns_name, {}).pop(payload["key"], None)
            self._mark_dirty()
            if ns_name != "__collective__":
                self._repl_append_locked(
                    "kv_del", {"ns": ns_name, "key": payload["key"]}
                )
        return {"ok": True}

    def rpc_kv_keys(self, payload, peer):
        with self._lock:
            ns = self._kv.get(payload.get("ns", "default"), {})
            pre = payload.get("prefix", b"")
            return [k for k in ns if k.startswith(pre)]

    # -- object directory -----------------------------------------------------

    def rpc_add_object_location(self, payload, peer):
        with self._lock:
            self._objects.setdefault(payload["object_id"], set()).add(
                payload["node_id"]
            )
        return {"ok": True}

    def rpc_remove_object_location(self, payload, peer):
        with self._lock:
            locs = self._objects.get(payload["object_id"])
            if locs is not None:
                locs.discard(payload["node_id"])
                if not locs:
                    self._objects.pop(payload["object_id"], None)
        return {"ok": True}

    def rpc_locate_object(self, payload, peer):
        with self._lock:
            locs = self._objects.get(payload["object_id"], set())
            return [
                self._nodes[nid].addr
                for nid in locs
                if nid in self._nodes and self._nodes[nid].alive
            ]

    def rpc_locate_many(self, payload, peer):
        """Batched location probe: object_id -> [holder addrs]. One RPC
        for a whole wait() poll / batched-fetch round instead of one per
        ref (empty list = not available, truthiness works for wait)."""
        with self._lock:
            out = {}
            for oid in payload["object_ids"]:
                locs = self._objects.get(oid, set())
                out[oid] = [
                    self._nodes[nid].addr
                    for nid in locs
                    if nid in self._nodes and self._nodes[nid].alive
                ]
            return out

    # -- actors ---------------------------------------------------------------

    def rpc_register_actor(self, payload, peer):
        with self._lock:
            name, ns = payload.get("name"), payload.get("namespace", "default")
            prior = self._actors.get(payload["actor_id"])
            if prior is not None and prior.state != "DEAD":
                # duplicate delivery: the client retried after losing the
                # ack (GCS failover/timeout) but the registration already
                # took. Ack idempotently — re-creating the entry would
                # reset restart bookkeeping, and the name check below
                # would bounce our OWN registration as "taken"
                return {"ok": True, "duplicate": True}
            if name:
                existing = self._named.get((ns, name))
                if existing is not None and existing != payload["actor_id"]:
                    a = self._actors.get(existing)
                    if a is not None and a.state != "DEAD":
                        return {"ok": False, "error": f"name {name!r} taken"}
            a = ActorEntry(
                actor_id=payload["actor_id"],
                name=name,
                namespace=ns,
                node_id=payload.get("node_id"),
                worker_addr=tuple(payload["worker_addr"]) if payload.get("worker_addr") else None,
                state=payload.get("state", "PENDING"),
                max_restarts=payload.get("max_restarts", 0),
                creation_spec=payload.get("creation_spec"),
                owner_addr=tuple(payload["owner_addr"]) if payload.get("owner_addr") else None,
                lease_resources=dict(
                    payload.get("lease", {}).get("resources", {"num_cpus": 1})
                ),
                lease_id=payload.get("lease_id"),
                node_addr=tuple(payload["node_addr"]) if payload.get("node_addr") else None,
            )
            self._actors[a.actor_id] = a
            if name:
                self._named[(ns, name)] = a.actor_id
            self._mark_dirty()
            self._repl_append_locked("actor_put", self._actor_info(a))
        # write-ahead ack: the registration must be durable BEFORE the
        # client sees ok — killing the GCS between this ack and the next
        # debounced sweep used to silently lose the actor
        self.persist_critical()
        return {"ok": True}

    def rpc_update_actor(self, payload, peer):
        with self._lock:
            a = self._actors.get(payload["actor_id"])
            if a is None:
                return {"ok": False}
            for k in ("node_id", "state"):
                if k in payload:
                    setattr(a, k, payload[k])
            if "worker_addr" in payload:
                a.worker_addr = (
                    tuple(payload["worker_addr"]) if payload["worker_addr"] else None
                )
            self._emit(
                "actor_update", {"actor_id": a.actor_id, "state": a.state}
            )
            self._mark_dirty()
            died = a.state == "DEAD"
        if died:
            # a kill is a critical mutation too: an unpersisted tombstone
            # lets the reconcile path resurrect an actor the user killed
            self.persist_critical()
        return {"ok": True}

    def _actor_info(self, a: ActorEntry) -> dict:
        return {
            "actor_id": a.actor_id,
            "name": a.name,
            "namespace": a.namespace,
            "node_id": a.node_id,
            "worker_addr": a.worker_addr,
            "state": a.state,
            "max_restarts": a.max_restarts,
            "num_restarts": a.num_restarts,
            "creation_spec": a.creation_spec,
            "owner_addr": a.owner_addr,
            "lease_id": a.lease_id,
            "node_addr": a.node_addr,
            "lease_resources": dict(a.lease_resources),
        }

    def rpc_get_actor(self, payload, peer):
        with self._lock:
            a = self._actors.get(payload["actor_id"])
            return self._actor_info(a) if a else None

    def rpc_get_named_actor(self, payload, peer):
        with self._lock:
            aid = self._named.get(
                (payload.get("namespace", "default"), payload["name"])
            )
            a = self._actors.get(aid) if aid else None
            return self._actor_info(a) if a else None

    def rpc_list_actors(self, payload, peer):
        with self._lock:
            return [self._actor_info(a) for a in self._actors.values()]

    # -- placement groups -----------------------------------------------------

    def rpc_create_pg(self, payload, peer):
        """Place bundles against the resource view. Returns the placement
        (bundle index -> node) or state=PENDING when it doesn't fit."""
        with self._lock:
            prior = self._pgs.get(payload["pg_id"])
            if prior is not None and prior["state"] != "REMOVED":
                # duplicate delivery (retry across a failover/timeout):
                # re-placing would deduct node availability a SECOND time
                # for the same bundles — return the existing placement
                return self._pg_info(prior)
            pg = {
                "pg_id": payload["pg_id"],
                "bundles": [
                    {"resources": dict(b), "node_id": None}
                    for b in payload["bundles"]
                ],
                "strategy": payload.get("strategy", "PACK"),
                "state": "PENDING",
                "name": payload.get("name"),
            }
            self._pgs[pg["pg_id"]] = pg
            self._try_place_pg(pg)
            self._mark_dirty()
            self._repl_append_locked("pg_put", self._pg_repl(pg))
            info = self._pg_info(pg)
        # write-ahead ack (same contract as register_actor): the
        # reservation the client is about to make against this placement
        # must survive a control-plane crash after the ack
        self.persist_critical()
        return info

    def _try_place_pg(self, pg: dict) -> None:
        alive = [e for e in self._nodes.values() if e.alive and not e.draining]
        if not alive:
            return
        strategy = pg["strategy"]
        # work on a copy of the availability view; commit on success
        avail = {e.node_id: dict(e.available) for e in alive}

        def fits(node_id: str, res: dict) -> bool:
            a = avail[node_id]
            return all(a.get(k, 0.0) >= v for k, v in res.items())

        def take(node_id: str, res: dict) -> None:
            a = avail[node_id]
            for k, v in res.items():
                a[k] = a.get(k, 0.0) - v

        assignment: list[Optional[str]] = [None] * len(pg["bundles"])
        order = sorted(avail)  # deterministic
        if strategy in ("STRICT_PACK",):
            for nid in order:
                trial = dict(avail[nid])
                ok = True
                for b in pg["bundles"]:
                    if all(trial.get(k, 0.0) >= v for k, v in b["resources"].items()):
                        for k, v in b["resources"].items():
                            trial[k] = trial.get(k, 0.0) - v
                    else:
                        ok = False
                        break
                if ok:
                    assignment = [nid] * len(pg["bundles"])
                    break
        elif strategy in ("STRICT_SPREAD", "SPREAD"):
            used: set[str] = set()
            for i, b in enumerate(pg["bundles"]):
                placed = False
                for nid in order:
                    if nid in used and strategy == "STRICT_SPREAD":
                        continue
                    if fits(nid, b["resources"]):
                        take(nid, b["resources"])
                        assignment[i] = nid
                        used.add(nid)
                        placed = True
                        break
                if not placed and strategy == "SPREAD":
                    # SPREAD is best-effort: reuse nodes
                    for nid in order:
                        if fits(nid, b["resources"]):
                            take(nid, b["resources"])
                            assignment[i] = nid
                            placed = True
                            break
                if not placed:
                    assignment = [None] * len(pg["bundles"])
                    break
        else:  # PACK: prefer one node, overflow to others
            for i, b in enumerate(pg["bundles"]):
                placed = False
                for nid in order:
                    if fits(nid, b["resources"]):
                        take(nid, b["resources"])
                        assignment[i] = nid
                        placed = True
                        break
                if not placed:
                    assignment = [None] * len(pg["bundles"])
                    break

        if all(a is not None for a in assignment):
            for b, nid in zip(pg["bundles"], assignment):
                b["node_id"] = nid
            if pg["state"] == "RESCHEDULING":
                # node-death re-placement: the CLIENT reserved the original
                # bundles at create time, but nobody is waiting to reserve
                # the replacements — the pg_reserve_sweep must do it, or
                # every lease against the re-placed bundle fails with "no
                # bundle reserved here" forever (chaos-found bug). The
                # generation counter lets the sweep detect a re-placement
                # that raced its (lock-free) reserve RPCs.
                pg["needs_reserve"] = True
                pg["reserve_gen"] = pg.get("reserve_gen", 0) + 1
            pg["state"] = "CREATED"
            # deduct from the authoritative view so back-to-back PGs don't
            # double-book before the next heartbeat refreshes availability
            for b, nid in zip(pg["bundles"], assignment):
                node = self._nodes.get(nid)
                if node is not None:
                    for k, v in b["resources"].items():
                        node.available[k] = node.available.get(k, 0.0) - v

    def rpc_remove_pg(self, payload, peer):
        with self._lock:
            pg = self._pgs.pop(payload["pg_id"], None)
            if pg is not None:
                # restore the authoritative availability view NOW — waiting
                # for the next heartbeat (0.5s) would serialize PG churn
                # (create/remove rate) on the heartbeat period
                for b in pg["bundles"]:
                    node = self._nodes.get(b.get("node_id"))
                    if node is not None:
                        for k, v in b["resources"].items():
                            node.available[k] = node.available.get(k, 0.0) + v
                pg["state"] = "REMOVED"
                self._emit("pg_update", {"pg_id": pg["pg_id"], "state": "REMOVED"})
            self._mark_dirty()
        return {"ok": True}

    def rpc_get_pg(self, payload, peer):
        with self._lock:
            pg = self._pgs.get(payload["pg_id"])
            if pg is not None and pg["state"] in ("PENDING", "RESCHEDULING"):
                prev = pg["state"]
                self._try_place_pg(pg)  # retry on demand (nodes may have joined)
                if pg["state"] != prev:
                    # an on-demand placement is the same durable mutation
                    # a create is: persist (debounced) and replicate it
                    self._mark_dirty()
                    self._repl_append_locked("pg_put", self._pg_repl(pg))
            return self._pg_info(pg) if pg else None

    def rpc_list_pgs(self, payload, peer):
        with self._lock:
            return [self._pg_info(pg) for pg in self._pgs.values()]

    def _pg_info(self, pg: dict) -> dict:
        return {
            "pg_id": pg["pg_id"],
            "bundles": [dict(b) for b in pg["bundles"]],
            "strategy": pg["strategy"],
            "state": pg["state"],
            "name": pg.get("name"),
        }


def start_sweeper(service: GcsService, stop: threading.Event,
                  pool=None, period_s: float = 0.25) -> threading.Thread:
    """The serving primary's background loop: health leases, reconcile
    convergence, actor restarts, PG re-reservation, debounced persist.
    Shared by GcsServer and by a promoted standby (cluster/ha.py) — a
    promotion must start EXACTLY this loop or the r13 fault-tolerance
    sweeps silently stop running on the new primary."""
    from ray_tpu.cluster.rpc import ClientPool

    if pool is None:
        pool = ClientPool(timeout=120.0)

    def sweep():
        while not stop.wait(period_s):
            try:
                service.health_sweep()
                service.reconcile_sweep(pool)
                service.restart_sweep(pool)
                service.pg_reserve_sweep(pool)
                service.persist_if_dirty()
            except Exception:
                logger.exception("health sweep failed")

    t = threading.Thread(target=sweep, name="gcs-health", daemon=True)
    t.start()
    return t


class GcsServer:
    """GcsService + RpcServer + health sweeper, embeddable or standalone."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 node_death_timeout_s: float = 5.0,
                 persist_path: Optional[str] = None):
        self.service = GcsService(
            node_death_timeout_s=node_death_timeout_s,
            persist_path=persist_path,
        )
        self.rpc = RpcServer(self.service, host=host, port=port)
        self._sweeper: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> tuple[str, int]:
        addr = self.rpc.start()
        self._sweeper = start_sweeper(self.service, self._stop)
        return addr

    def stop(self) -> None:
        self._stop.set()
        self.rpc.stop()


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--death-timeout", type=float, default=5.0)
    p.add_argument("--persist", default=None,
                   help="snapshot path for GCS fault tolerance")
    args = p.parse_args()
    server = GcsServer(args.host, args.port, args.death_timeout,
                       persist_path=args.persist)
    host, port = server.start()
    # parent discovers the bound port from stdout
    print(f"GCS_ADDRESS {host}:{port}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
