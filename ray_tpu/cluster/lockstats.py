"""Lock hold/wait and per-RPC latency instrumentation for the control
plane (obs.perfwatch probe: the before-picture GCS sharding is graded
against).

The GCS serializes every table behind ONE ``RLock`` domain
(gcs_service.py). Before that domain can be partitioned, the roadmap
needs distributions, not vibes: how long do callers WAIT for the lock,
how long does the holder KEEP it, and which RPC methods pay. This
module provides:

 * ``TimedRLock`` — a thin wrapper around ``threading.RLock`` that
   feeds wait-time (outermost acquire) and hold-time (outermost
   release) histograms, tagged by lock domain. When timing is disabled
   (the default) acquire/release cost one attribute load and an integer
   add on top of the raw RLock — no clock reads, no histogram locks.
   The wrapper implements the ``_release_save`` / ``_acquire_restore``
   / ``_is_owned`` protocol so ``threading.Condition(TimedRLock(...))``
   works unchanged (the GCS event pubsub builds exactly that).
 * per-RPC-method server latency histograms (``RpcServer._dispatch``
   observes them), pricing each control-plane method end to end —
   executor queueing included, response write excluded.

Enable with ``enable_lock_timing()`` (the locks bench and the perf
sampler do); production code pays the fast path until someone asks.
"""

from __future__ import annotations

import threading
import time

# lock waits/holds and RPC dispatch on the control plane are sub-ms to
# tens-of-ms; default bucket ladder tops out too coarse for that
_LATENCY_BOUNDARIES_MS = [
    0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 1000.0,
]

# module-level switch read on every acquire: a list cell (not a bare
# bool) so the flag flip is visible through the closure without globals
_ENABLED = [False]


def enable_lock_timing(on: bool = True) -> None:
    """Turn hold/wait histogram feeds on (off = the near-zero fast
    path). Process-wide: every TimedRLock domain follows the switch."""
    _ENABLED[0] = bool(on)


def lock_timing_enabled() -> bool:
    return _ENABLED[0]


def lock_wait_histogram():
    """Time callers spend blocked on an outermost acquire, by domain —
    the contention signal: ~0 uncontended regardless of hold times."""
    from ray_tpu.obs.telemetry import cluster_histogram

    return cluster_histogram(
        "controlplane_lock_wait_ms",
        description="wall time blocked acquiring a control-plane lock "
        "(outermost acquire only), by lock domain",
        boundaries=_LATENCY_BOUNDARIES_MS,
        tag_keys=("domain",),
    )


def lock_hold_histogram():
    """Time the holder keeps the lock (outermost acquire -> outermost
    release), by domain — long holds are what sharding would split."""
    from ray_tpu.obs.telemetry import cluster_histogram

    return cluster_histogram(
        "controlplane_lock_hold_ms",
        description="wall time a control-plane lock is held (outermost "
        "acquire to outermost release), by lock domain",
        boundaries=_LATENCY_BOUNDARIES_MS,
        tag_keys=("domain",),
    )


def rpc_latency_histogram():
    """Server-side RPC latency by method: handler execution including
    executor queueing, excluding the response write."""
    from ray_tpu.obs.telemetry import cluster_histogram

    return cluster_histogram(
        "controlplane_rpc_latency_ms",
        description="server-side control-plane RPC handler latency by "
        "method (executor queueing included, response write excluded)",
        boundaries=_LATENCY_BOUNDARIES_MS,
        tag_keys=("method",),
    )


def register_metrics() -> None:
    """scripts/check_metrics.py hook: force lazy metrics to register."""
    lock_wait_histogram()
    lock_hold_histogram()
    rpc_latency_histogram()


class TimedRLock:
    """``threading.RLock`` with optional hold/wait histograms.

    Reentrancy depth is tracked unconditionally (an integer add by the
    holder, already serialized by the lock itself) so timing can be
    flipped on mid-flight without corrupting the outermost-release
    bookkeeping. Clock reads and histogram observes happen only while
    ``enable_lock_timing`` is on, and only at the OUTERMOST
    acquire/release — reentrant hops stay free.
    """

    def __init__(self, domain: str):
        self._lk = threading.RLock()
        self._domain = domain
        self._depth = 0        # mutated only by the current holder
        self._t_hold0 = 0.0    # outermost-acquire timestamp (0 = untimed)

    # -- core lock protocol ---------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _ENABLED[0]:
            ok = self._lk.acquire(blocking, timeout)
            if ok:
                self._depth += 1
            return ok
        t0 = time.perf_counter()
        ok = self._lk.acquire(blocking, timeout)
        if not ok:
            return False
        self._depth += 1
        if self._depth == 1:
            now = time.perf_counter()
            lock_wait_histogram().observe(
                (now - t0) * 1e3, {"domain": self._domain}
            )
            self._t_hold0 = now
        return True

    def release(self) -> None:
        if self._depth == 1 and self._t_hold0:
            # timing may have been disabled mid-hold: the observe is
            # gated on the recorded start, not on the current switch
            lock_hold_histogram().observe(
                (time.perf_counter() - self._t_hold0) * 1e3,
                {"domain": self._domain},
            )
            self._t_hold0 = 0.0
        self._depth -= 1
        self._lk.release()

    def __enter__(self) -> "TimedRLock":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()

    # -- Condition protocol ---------------------------------------------------
    # threading.Condition(lock) delegates to these when present; wait()
    # fully releases a reentrant lock and restores its depth after.

    def _release_save(self):
        if self._t_hold0:
            lock_hold_histogram().observe(
                (time.perf_counter() - self._t_hold0) * 1e3,
                {"domain": self._domain},
            )
            self._t_hold0 = 0.0
        depth, self._depth = self._depth, 0
        return (self._lk._release_save(), depth)

    def _acquire_restore(self, saved) -> None:
        state, depth = saved
        timing = _ENABLED[0]
        t0 = time.perf_counter() if timing else 0.0
        self._lk._acquire_restore(state)
        self._depth = depth
        if timing:
            now = time.perf_counter()
            lock_wait_histogram().observe(
                (now - t0) * 1e3, {"domain": self._domain}
            )
            self._t_hold0 = now

    def _is_owned(self) -> bool:
        return self._lk._is_owned()
